"""Pure-Python NIST P-256 reference implementation (correctness oracle).

This is the host-side oracle the TPU kernel (`fabric_tpu.ops.p256`) is
tested bit-exactly against, and the arithmetic backing for key/cert
generation where the `cryptography` package is not used.  Semantics
mirror the reference's SW BCCSP verifier: ECDSA P-256 with SHA-256
digests and the low-S rule (reference: bccsp/sw/ecdsa.go:41-58 —
signatures with s > n/2 are rejected; signing normalizes s to low-S).

Python ints only; NOT constant-time; verify-only paths don't need to be.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass

# NIST P-256 (secp256r1) domain parameters.
P = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
A = P - 3
B = 0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B
N = 0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551
GX = 0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296
GY = 0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5
HALF_N = N >> 1

INF = None  # point at infinity


def is_on_curve(pt) -> bool:
    if pt is INF:
        return True
    x, y = pt
    return (y * y - (x * x * x + A * x + B)) % P == 0


def pt_add(p1, p2):
    if p1 is INF:
        return p2
    if p2 is INF:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return INF
        return pt_double(p1)
    lam = ((y2 - y1) * pow(x2 - x1, -1, P)) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def pt_double(pt):
    if pt is INF:
        return INF
    x, y = pt
    if y == 0:
        return INF
    lam = ((3 * x * x + A) * pow(2 * y, -1, P)) % P
    x3 = (lam * lam - 2 * x) % P
    y3 = (lam * (x - x3) - y) % P
    return (x3, y3)


def pt_mul(k: int, pt):
    k %= N
    acc = INF
    addend = pt
    while k:
        if k & 1:
            acc = pt_add(acc, addend)
        addend = pt_double(addend)
        k >>= 1
    return acc


G = (GX, GY)


@dataclass(frozen=True)
class SigningKey:
    d: int  # private scalar in [1, n-1]

    @property
    def public(self):
        return pt_mul(self.d, G)

    @classmethod
    def generate(cls) -> "SigningKey":
        return cls(d=secrets.randbelow(N - 1) + 1)

    def sign_digest(self, e: int, k: int | None = None) -> tuple[int, int]:
        """ECDSA sign; returns low-S normalized (r, s)."""
        while True:
            kk = k if k is not None else secrets.randbelow(N - 1) + 1
            x1, _ = pt_mul(kk, G)
            r = x1 % N
            if r == 0:
                if k is not None:
                    raise ValueError("bad fixed k")
                continue
            s = (pow(kk, -1, N) * (e + r * self.d)) % N
            if s == 0:
                if k is not None:
                    raise ValueError("bad fixed k")
                continue
            if s > HALF_N:
                s = N - s  # low-S normalization (bccsp/sw/ecdsa.go ToLowS)
            return r, s

    def sign(self, msg: bytes) -> tuple[int, int]:
        return self.sign_digest(digest_int(msg))


def digest_int(msg: bytes) -> int:
    return int.from_bytes(hashlib.sha256(msg).digest(), "big")


def verify_digest(pub, e: int, r: int, s: int) -> bool:
    """Reference verify incl. Fabric's low-S rule."""
    if pub is INF or not (0 <= pub[0] < P and 0 <= pub[1] < P) or not is_on_curve(pub):
        return False
    if not (1 <= r < N and 1 <= s < N):
        return False
    if s > HALF_N:  # low-S enforcement per bccsp/sw/ecdsa.go:41-58
        return False
    w = pow(s, -1, N)
    u1 = (e * w) % N
    u2 = (r * w) % N
    pt = pt_add(pt_mul(u1, G), pt_mul(u2, pub))
    if pt is INF:
        return False
    return pt[0] % N == r % N


def verify(pub, msg: bytes, r: int, s: int) -> bool:
    return verify_digest(pub, digest_int(msg), r, s)

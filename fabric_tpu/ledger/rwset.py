"""Read/write-set build & parse (analog of the reference's rwsetutil,
core/ledger/kvledger/txmgmt/rwsetutil/rwset_proto_util.go).

Two representations:

* proto wire form (fabric_tpu.protos.rwset_pb2) — what travels inside
  ChaincodeAction.results;
* host form (``TxRWSet`` below) — namespace-keyed dict of reads/writes/
  range-queries that the simulator builds and the MVCC preparation
  (fabric_tpu.ops.mvcc.prepare_block) flattens into device arrays.

Hashed private-collection reads/writes (reference: validator.go:249-283)
carry (namespace, collection, key_hash) keys — disjoint from public
(namespace, key) keys by construction of the key tuples.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from fabric_tpu.protos import rwset_pb2


Version = tuple[int, int]  # (block_num, tx_num)

# the metadata entry carrying a key-level endorsement policy (a
# serialized SignaturePolicyEnvelope) — reference:
# core/ledger/util/couchdb… pb.MetaDataKeys / shim
# SetStateValidationParameter (statebased/validator_keylevel.go)
VALIDATION_PARAMETER = "VALIDATION_PARAMETER"


def encode_metadata(entries: dict) -> bytes | None:
    """{name: value} → stable wire bytes for statedb storage (an empty
    map means the metadata was CLEARED → None).  Reuses the
    KVMetadataWrite message with an empty key as the container."""
    if not entries:
        return None
    mw = rwset_pb2.KVMetadataWrite(key="")
    for name in sorted(entries):
        mw.entries.add(name=name, value=entries[name])
    return mw.SerializeToString()


def decode_metadata(raw: bytes | None) -> dict:
    if not raw:
        return {}
    mw = rwset_pb2.KVMetadataWrite()
    mw.ParseFromString(raw)
    return {e.name: e.value for e in mw.entries}


@dataclass
class NsRWSet:
    reads: dict = field(default_factory=dict)        # key -> Version | None
    writes: dict = field(default_factory=dict)       # key -> bytes | None (None = delete)
    range_queries: list = field(default_factory=list)  # (start, end, [(key, ver)])
    metadata_writes: dict = field(default_factory=dict)  # key -> {name: bytes}
    # collection -> {"reads": {key_hash: ver}, "writes": {key_hash: (value_hash, is_delete)}}
    hashed: dict = field(default_factory=dict)


@dataclass
class TxRWSet:
    ns: dict = field(default_factory=dict)  # namespace -> NsRWSet

    def ns_rwset(self, namespace: str) -> NsRWSet:
        return self.ns.setdefault(namespace, NsRWSet())

    # -- wire form ---------------------------------------------------------

    def to_proto(self) -> rwset_pb2.TxReadWriteSet:
        out = rwset_pb2.TxReadWriteSet(data_model=rwset_pb2.TxReadWriteSet.KV)
        for name in sorted(self.ns):
            n = self.ns[name]
            kv = rwset_pb2.KVRWSet()
            for k in sorted(n.reads):
                r = kv.reads.add(key=k)
                ver = n.reads[k]
                if ver is not None:
                    r.version.block_num, r.version.tx_num = ver
            for start, end, results in n.range_queries:
                rq = kv.range_queries_info.add(
                    start_key=start, end_key=end, itr_exhausted=True
                )
                for k, ver in results:
                    r = rq.raw_reads.kv_reads.add(key=k)
                    if ver is not None:
                        r.version.block_num, r.version.tx_num = ver
            for k in sorted(n.writes):
                v = n.writes[k]
                kv.writes.add(key=k, is_delete=v is None, value=v or b"")
            for k in sorted(n.metadata_writes):
                mw = kv.metadata_writes.add(key=k)
                for mname in sorted(n.metadata_writes[k]):
                    mw.entries.add(name=mname, value=n.metadata_writes[k][mname])
            ns_pb = out.ns_rwset.add(namespace=name, rwset=kv.SerializeToString())
            for coll in sorted(n.hashed):
                h = rwset_pb2.HashedRWSet()
                cdata = n.hashed[coll]
                for kh in sorted(cdata.get("reads", {})):
                    hr = h.hashed_reads.add(key_hash=kh)
                    ver = cdata["reads"][kh]
                    if ver is not None:
                        hr.version.block_num, hr.version.tx_num = ver
                for kh in sorted(cdata.get("writes", {})):
                    vh, is_del = cdata["writes"][kh]
                    h.hashed_writes.add(key_hash=kh, value_hash=vh, is_delete=is_del)
                ns_pb.collection_hashed_rwset.add(
                    collection_name=coll,
                    hashed_rwset=h.SerializeToString(),
                    pvt_rwset_hash=cdata.get("pvt_hash", b""),
                )
        return out

    @classmethod
    def from_proto(cls, pb: rwset_pb2.TxReadWriteSet) -> "TxRWSet":
        tx = cls()
        for ns_pb in pb.ns_rwset:
            n = tx.ns_rwset(ns_pb.namespace)
            kv = rwset_pb2.KVRWSet()
            kv.ParseFromString(ns_pb.rwset)
            for r in kv.reads:
                n.reads[r.key] = (
                    (r.version.block_num, r.version.tx_num)
                    if r.HasField("version")
                    else None
                )
            for rq in kv.range_queries_info:
                results = [
                    (
                        r.key,
                        (r.version.block_num, r.version.tx_num)
                        if r.HasField("version")
                        else None,
                    )
                    for r in rq.raw_reads.kv_reads
                ]
                n.range_queries.append((rq.start_key, rq.end_key, results))
            for w in kv.writes:
                n.writes[w.key] = None if w.is_delete else w.value
            for mw in kv.metadata_writes:
                n.metadata_writes[mw.key] = {e.name: e.value for e in mw.entries}
            for coll in ns_pb.collection_hashed_rwset:
                h = rwset_pb2.HashedRWSet()
                h.ParseFromString(coll.hashed_rwset)
                cdata = {"reads": {}, "writes": {}, "pvt_hash": coll.pvt_rwset_hash}
                for hr in h.hashed_reads:
                    cdata["reads"][hr.key_hash] = (
                        (hr.version.block_num, hr.version.tx_num)
                        if hr.HasField("version")
                        else None
                    )
                for hw in h.hashed_writes:
                    cdata["writes"][hw.key_hash] = (hw.value_hash, hw.is_delete)
                n.hashed[coll.collection_name] = cdata
        return tx

    @classmethod
    def from_bytes(cls, data: bytes) -> "TxRWSet":
        pb = rwset_pb2.TxReadWriteSet()
        pb.ParseFromString(data)
        return cls.from_proto(pb)

    # -- MVCC kernel form --------------------------------------------------

    def mvcc_form(self):
        """→ (reads, writes, range_reads) with composite keys for
        fabric_tpu.ops.mvcc.TxRWSet.  Public keys are ('pub', ns, key);
        hashed collection keys ('pvt', ns, coll, key_hash) — disjoint
        spaces, one dense id universe per block."""
        reads, writes, rqs = [], [], []
        for name in sorted(self.ns):
            n = self.ns[name]
            for k, ver in sorted(n.reads.items()):
                reads.append((("pub", name, k), ver))
            for k in sorted(n.writes):
                writes.append(("pub", name, k))
            # metadata-only writes are STATE-DEPENDENT writers (they
            # bump the version only when the key exists) — the
            # validator's _mvcc_inputs adds them with the existence
            # check; this pure form stays state-independent
            for start, end, results in n.range_queries:
                for k, ver in results:
                    reads.append((("pub", name, k), ver))
                # end == "" is an unbounded (to namespace end) scan;
                # ns+"\x00" sorts after every ("pub", name, k) key, so
                # the id interval covers the whole namespace
                hi = ("pub", name, end) if end else ("pub", name + "\x00", "")
                rqs.append((("pub", name, start), hi))
            for coll in sorted(n.hashed):
                cdata = n.hashed[coll]
                for kh, ver in sorted(cdata.get("reads", {}).items()):
                    reads.append((("pvt", name, coll, kh), ver))
                for kh in sorted(cdata.get("writes", {})):
                    writes.append(("pvt", name, coll, kh))
        return reads, writes, rqs

"""Config history: chaincode-definition (incl. collection config)
versions by commit height.

Reference: core/ledger/confighistory/mgr.go — the committer records
each namespace's collection config at the block that changed it, so
the pvtdata reconciler can answer "what did ns X's config say at block
N" for eligibility decisions on OLD blocks."""

from __future__ import annotations

import sqlite3


class ConfigHistoryDB:
    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS confighistory ("
            " ns TEXT, block INTEGER, definition BLOB,"
            " PRIMARY KEY (ns, block))"
        )
        self._conn.commit()

    def record(self, block: int, ns: str, definition: bytes) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO confighistory VALUES (?,?,?)",
            (ns, block, definition),
        )
        self._conn.commit()

    def most_recent_below(self, ns: str, block: int):
        """→ (committed_block, definition_bytes) | None: the definition
        governing ns at height ``block`` (mgr.go MostRecentEntryBelow)."""
        row = self._conn.execute(
            "SELECT block, definition FROM confighistory"
            " WHERE ns=? AND block<=? ORDER BY block DESC LIMIT 1",
            (ns, block),
        ).fetchone()
        return (row[0], row[1]) if row else None

    def close(self):
        self._conn.close()

"""Versioned state database: SPI + memory and sqlite backends.

Analog of the reference's statedb layer
(core/ledger/kvledger/txmgmt/statedb/statedb.go:36-76 ``VersionedDB``):
keyed (namespace, key) → (value, metadata, version), bulk reads, range
scans, savepoints.  Two backends mirror the reference's split:

* ``MemVersionedDB`` — in-process dict (test/bench fixture, the analog
  of statedb's mock+leveldb-in-memory usage);
* ``SqliteVersionedDB`` — durable embedded store (the goleveldb
  analog); rich JSON queries via sqlite's json functions stand in for
  the CouchDB backend (statecouchdb) without an external service —
  the reference itself documents CouchDB as a throughput liability
  (docs/source/performance.md:180-186).

The TPU-relevant member is ``get_versions_bulk``: one gather of
committed versions for every read key of a block, feeding
fabric_tpu.ops.mvcc.prepare_block (the reference bulk-preload:
txmgmt/validation/validator.go:27-78).
"""

from __future__ import annotations

import json
import sqlite3
from bisect import bisect_left
from dataclasses import dataclass

Version = tuple[int, int]


@dataclass
class VersionedValue:
    value: bytes | None
    metadata: bytes | None
    version: Version


class UpdateBatch:
    """Accumulated writes of a block (analog statedb.UpdateBatch)."""

    def __init__(self):
        self.updates: dict = {}  # (ns, key) -> VersionedValue (value None = delete)
        self.has_meta = False    # any entry carries key metadata (SBE)

    def put(self, ns: str, key: str, value: bytes | None, version: Version, metadata: bytes | None = None):
        if metadata:
            self.has_meta = True
        self.updates[(ns, key)] = VersionedValue(value, metadata, version)

    def delete(self, ns: str, key: str, version: Version):
        self.put(ns, key, None, version)

    def items(self):
        return self.updates.items()

    def touches_namespace(self, ns: str) -> bool:
        """True when any entry writes ``ns`` — the lifecycle-barrier
        and post-commit scans use this instead of walking (and, for
        the columnar form, materializing) the full update dict."""
        return any(k[0] == ns for k in self.updates)

    @classmethod
    def merged(cls, batches):
        """One overlay view over a CHAIN of in-flight predecessor
        batches, oldest first — the depth-N commit pipeline's launch
        overlay (peer/pipeline.py).  Key resolution is newest-wins
        (``dict.update`` in chain order: exactly the value the LAST
        in-flight apply will land, so an overridden read equals a
        serialized read), ``has_meta`` is the union (a key-metadata
        write anywhere in the window must keep the successor's SBE
        machinery engaged), and iteration covers every key any
        predecessor touched (the lifecycle-write veto and range
        re-execution walk the whole window).

        Returns None for an empty chain and the batch ITSELF for a
        singleton — the depth-2 fast path stays pointer-identical to
        the single-overlay behavior every existing test pins."""
        batches = [b for b in batches if b is not None]
        if not batches:
            return None
        if len(batches) == 1:
            return batches[0]
        out = cls()
        for b in batches:
            out.updates.update(b.updates)
            if b.has_meta:
                out.has_meta = True
        return out


class ColumnarUpdateBatch(UpdateBatch):
    """Columnar UpdateBatch built straight off the validator's flat
    write slabs — no per-key Python tuples or VersionedValue objects
    on the commit path.

    Rows live in numpy arrays in FINAL APPLY ORDER (the concatenation,
    tx by tx, of each valid tx's (ns, key)-sorted writes — exactly the
    store order of ``_build_updates_flat``); key/namespace strings are
    indices into the block's shared unique-key table, and values are
    offset/length spans over the shared ``blob``.  The classic dict
    form stays available through the lazy ``updates`` property
    (identical content AND insertion order, so every overlay consumer
    — launch overlays, ``merged()``, the mem backend — behaves
    byte-for-byte like the dict batch), while
    ``SqliteVersionedDB.apply_updates`` consumes the slabs directly:
    one ``executemany`` per namespace, zero-copy memoryview value
    slices.

    ``put``/``delete`` after construction (the pvt hashed-write phase,
    BTL purge) land in a small ``_extra`` override dict that shadows
    the slab rows everywhere.
    """

    def __init__(self, block_num: int, ns_names: list, ukeys: list,
                 ns_of, row_uid, row_del, row_voff, row_vlen,
                 row_txnum, blob: bytes):
        # no super().__init__: ``updates`` is a lazy property here
        self.block_num = block_num
        self.ns_names = ns_names
        self.ukeys = ukeys
        self.ns_of = ns_of          # [n_keys] uid -> ns index
        self.row_uid = row_uid      # [R] apply-ordered key ids
        self.row_del = row_del      # [R] bool
        self.row_voff = row_voff    # [R] value span over blob
        self.row_vlen = row_vlen
        self.row_txnum = row_txnum  # [R] tx num (version minor)
        self.blob = blob
        self.has_meta = False
        self._extra: dict = {}      # post-build overrides
        self._updates: dict | None = None

    @property
    def updates(self):
        u = self._updates
        if u is None:
            # build into a local and publish last: readers on other
            # threads (the background applier vs. an overlay read) may
            # materialize concurrently — both build the same dict and
            # the single attribute store keeps it race-free
            u = self._materialize()
            self._updates = u
        return u

    def _materialize(self) -> dict:
        d: dict = {}
        ns_names, ukeys, ns_of = self.ns_names, self.ukeys, self.ns_of
        blob, bn = self.blob, self.block_num
        uid_l = self.row_uid.tolist()
        del_l = self.row_del.tolist()
        vo_l = self.row_voff.tolist()
        vl_l = self.row_vlen.tolist()
        tx_l = self.row_txnum.tolist()
        for r, uid in enumerate(uid_l):
            if del_l[r]:
                val = None
            else:
                vo = vo_l[r]
                val = blob[vo:vo + vl_l[r]]
            d[(ns_names[ns_of[uid]], ukeys[uid])] = VersionedValue(
                val, None, (bn, tx_l[r])
            )
        d.update(self._extra)
        return d

    def put(self, ns, key, value, version, metadata=None):
        if metadata:
            self.has_meta = True
        vv = VersionedValue(value, metadata, version)
        self._extra[(ns, key)] = vv
        if self._updates is not None:
            self._updates[(ns, key)] = vv

    def touches_namespace(self, ns: str) -> bool:
        if any(k[0] == ns for k in self._extra):
            return True
        try:
            idx = self.ns_names.index(ns)
        except ValueError:
            return False
        if not len(self.row_uid):
            return False
        import numpy as np

        return bool(np.any(np.asarray(self.ns_of)[self.row_uid] == idx))

    def sqlite_columns(self):
        """→ yields ``(deletes, rows)`` per namespace for the sqlite
        fast path: ``deletes`` = [(ns, key)], ``rows`` = executemany
        tuples with zero-copy memoryview value slices.  Per-key
        last-wins dedupe (a later tx's write of the same key shadows
        the earlier row, exactly like the dict build), and rows
        shadowed by ``_extra`` overrides are skipped — the caller
        applies the extras through the classic per-key path."""
        last: dict = {}  # uid -> last row index
        for r, uid in enumerate(self.row_uid.tolist()):
            last[uid] = r
        extras = self._extra
        ns_names, ukeys, ns_of = self.ns_names, self.ukeys, self.ns_of
        mv = memoryview(self.blob)
        bn = self.block_num
        per_ns_del: dict = {}
        per_ns_row: dict = {}
        for uid, r in last.items():
            ns = ns_names[ns_of[uid]]
            key = ukeys[uid]
            if extras and (ns, key) in extras:
                continue
            if self.row_del[r]:
                per_ns_del.setdefault(ns, []).append((ns, key))
            else:
                vo = int(self.row_voff[r])
                per_ns_row.setdefault(ns, []).append(
                    (ns, key, mv[vo:vo + int(self.row_vlen[r])], None,
                     bn, int(self.row_txnum[r]))
                )
        for ns in sorted(set(per_ns_del) | set(per_ns_row)):
            yield per_ns_del.get(ns, ()), per_ns_row.get(ns, ())

    def extra_items(self):
        return self._extra.items()


class VersionedDB:
    """SPI (statedb.go:36-76)."""

    # True when the backend persists across process crashes — the
    # kvledger uses this to keep the block store's durability AHEAD of
    # the state savepoint (a durable savepoint past the block files
    # would break crash recovery's replay-forward assumption)
    durable: bool = True

    def open(self) -> None: ...
    def close(self) -> None: ...

    def get_state(self, ns: str, key: str) -> VersionedValue | None:
        raise NotImplementedError

    def get_version(self, ns: str, key: str) -> Version | None:
        vv = self.get_state(ns, key)
        return vv.version if vv else None

    def get_versions_bulk(self, keys: list[tuple[str, str]]) -> dict:
        """{(ns, key): Version} for present keys — the block-level
        gather used by MVCC preparation."""
        out = {}
        for ns, key in keys:
            v = self.get_version(ns, key)
            if v is not None:
                out[(ns, key)] = v
        return out

    def get_versions_cols(self, keys: list[tuple[str, str]]):
        """Column form of :meth:`get_versions_bulk` for the validator's
        ``state_fill`` hot path: → ``(present [U] bool, vers [U, 2]
        uint32)`` numpy arrays positionally aligned with ``keys``.  The
        dict round-trip of ``get_versions_bulk`` (build a dict, then
        re-walk every key to probe it) cost a second Python pass over
        every unique read key per block; backends override this with a
        single fused pass."""
        import numpy as np

        U = len(keys)
        present = np.zeros(U, bool)
        vers = np.zeros((U, 2), np.uint32)
        got = self.get_versions_bulk(keys)
        if got:
            for i, k in enumerate(keys):
                v = got.get(k)
                if v is not None:
                    present[i] = True
                    vers[i] = v
        return present, vers

    def iter_all(self):
        """Yield ((ns, key), VersionedValue) over the WHOLE state in
        (ns, key) order — deterministic for snapshot hashing
        (kvledger/snapshot.go export ordering)."""
        raise NotImplementedError

    def get_state_range(self, ns: str, start: str, end: str, limit: int = 0):
        """Yield (key, VersionedValue) for start <= key < end in key
        order ('' end = unbounded)."""
        raise NotImplementedError

    def execute_query(self, ns: str, query: dict, limit: int = 0):
        raise NotImplementedError("rich queries unsupported by this backend")

    def apply_updates(self, batch: UpdateBatch, savepoint: Version | None) -> None:
        raise NotImplementedError

    def savepoint(self) -> Version | None:
        raise NotImplementedError


class MemVersionedDB(VersionedDB):
    """In-memory backend.  Range/query iteration takes a lock against
    concurrent apply_updates: the commit pipeline overlaps the
    predecessor's state commit (committer thread) with the next
    block's launch, whose range re-execution walks these structures —
    per-key read SEMANTICS under that overlap are handled by the
    validator's overlay, the lock only guards the dict/cache
    iteration itself."""

    durable = False  # dies with the process: always replay-recovered

    def __init__(self):
        import threading

        self._data: dict = {}  # (ns,key) -> VersionedValue
        self._sorted_cache: dict = {}  # ns -> sorted key list (invalidated on write)
        self._savepoint: Version | None = None
        self._lock = threading.Lock()
        # number of keys carrying non-null metadata (key-level
        # endorsement policies): the validator's SBE gate — blocks on a
        # channel with NO key-level policies anywhere skip the
        # metadata bulk-lookup entirely
        self.meta_count = 0

    def get_state(self, ns, key):
        return self._data.get((ns, key))  # dict.get is atomic under the GIL

    def get_versions_cols(self, keys):
        """Single fused pass (no intermediate dict): each lookup is one
        GIL-atomic ``dict.get`` — same concurrent-apply semantics as
        ``get_state``, the validator's overlay handles read ordering."""
        import numpy as np

        U = len(keys)
        present = np.zeros(U, bool)
        vers = np.zeros((U, 2), np.uint32)
        get = self._data.get
        for i, k in enumerate(keys):
            vv = get(k)
            if vv is not None:
                present[i] = True
                vers[i] = vv.version
        return present, vers

    def _sorted_keys(self, ns):
        keys = self._sorted_cache.get(ns)
        if keys is None:
            keys = sorted(k for (n, k) in self._data if n == ns)
            self._sorted_cache[ns] = keys
        return keys

    def iter_all(self):
        with self._lock:
            rows = [(k, self._data[k]) for k in sorted(self._data)]
        yield from rows

    def get_state_range(self, ns, start, end, limit=0):
        with self._lock:  # materialize under the lock, then yield
            keys = self._sorted_keys(ns)
            i = bisect_left(keys, start)
            rows = []
            while i < len(keys) and (not end or keys[i] < end):
                vv = self._data.get((ns, keys[i]))
                if vv is not None:
                    rows.append((keys[i], vv))
                i += 1
                if limit and len(rows) >= limit:
                    break
        yield from rows

    def execute_query(self, ns, query, limit=0):
        """CouchDB-selector-style equality matching over JSON values."""
        sel = query.get("selector", {})
        with self._lock:  # copy only the key list under the lock
            keys = list(self._sorted_keys(ns))
        n = 0
        for key in keys:
            vv = self._data.get((ns, key))  # atomic under the GIL
            if vv is None or vv.value is None:
                continue
            try:
                doc = json.loads(vv.value)
            except (ValueError, UnicodeDecodeError):
                continue
            if all(doc.get(f) == want for f, want in sel.items()):
                yield key, vv
                n += 1
                if limit and n >= limit:
                    return

    def apply_updates(self, batch, savepoint):
        with self._lock:
            for (ns, key), vv in batch.items():
                old = self._data.get((ns, key))
                if old is not None and old.metadata:
                    self.meta_count -= 1
                if vv.value is None:
                    self._data.pop((ns, key), None)
                else:
                    if vv.metadata:
                        self.meta_count += 1
                    self._data[(ns, key)] = vv
                self._sorted_cache.pop(ns, None)
        if savepoint is not None:
            self._savepoint = savepoint

    def savepoint(self):
        return self._savepoint


class SqliteVersionedDB(VersionedDB):
    """Durable backend over sqlite (WAL mode)."""

    def __init__(self, path: str):
        self.path = path
        self._conn: sqlite3.Connection | None = None

    def open(self):
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS state ("
            " ns TEXT NOT NULL, key TEXT NOT NULL,"
            " value BLOB, metadata BLOB,"
            " block INTEGER NOT NULL, txnum INTEGER NOT NULL,"
            " PRIMARY KEY (ns, key))"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS savepoint ("
            " id INTEGER PRIMARY KEY CHECK (id = 0),"
            " block INTEGER, txnum INTEGER)"
        )
        self._conn.commit()
        # SBE gate (see MemVersionedDB.meta_count)
        self.meta_count = self._conn.execute(
            "SELECT COUNT(*) FROM state WHERE metadata IS NOT NULL"
            " AND metadata != x''"
        ).fetchone()[0]

    def close(self):
        if self._conn:
            self._conn.close()
            self._conn = None

    def get_state(self, ns, key):
        row = self._conn.execute(
            "SELECT value, metadata, block, txnum FROM state WHERE ns=? AND key=?",
            (ns, key),
        ).fetchone()
        if row is None:
            return None
        return VersionedValue(row[0], row[1], (row[2], row[3]))

    def get_versions_bulk(self, keys):
        out = {}
        cur = self._conn.cursor()
        for ns, key in keys:
            row = cur.execute(
                "SELECT block, txnum FROM state WHERE ns=? AND key=?", (ns, key)
            ).fetchone()
            if row:
                out[(ns, key)] = (row[0], row[1])
        return out

    def get_versions_cols(self, keys):
        """Fused column gather: one cursor, arrays filled in place —
        no per-key dict churn on the state_fill hot path."""
        import numpy as np

        U = len(keys)
        present = np.zeros(U, bool)
        vers = np.zeros((U, 2), np.uint32)
        cur = self._conn.cursor()
        for i, (ns, key) in enumerate(keys):
            row = cur.execute(
                "SELECT block, txnum FROM state WHERE ns=? AND key=?",
                (ns, key),
            ).fetchone()
            if row:
                present[i] = True
                vers[i] = row
        return present, vers

    def iter_all(self):
        q = ("SELECT ns, key, value, metadata, block, txnum FROM state "
             "ORDER BY ns, key")
        for ns, key, value, md, blk, txn in self._conn.execute(q):
            yield (ns, key), VersionedValue(value, md, (blk, txn))

    def get_state_range(self, ns, start, end, limit=0):
        q = "SELECT key, value, metadata, block, txnum FROM state WHERE ns=? AND key>=?"
        args = [ns, start]
        if end:
            q += " AND key<?"
            args.append(end)
        q += " ORDER BY key"
        if limit:
            q += f" LIMIT {int(limit)}"
        for key, value, md, blk, txn in self._conn.execute(q, args):
            yield key, VersionedValue(value, md, (blk, txn))

    def execute_query(self, ns, query, limit=0):
        """Rich queries via sqlite JSON1 (statecouchdb analog)."""
        sel = query.get("selector", {})
        clauses, args = [], [ns]
        for fld, want in sel.items():
            clauses.append("json_extract(value, ?) = ?")
            args.append(f"$.{fld}")
            args.append(want)
        q = "SELECT key, value, metadata, block, txnum FROM state WHERE ns=?"
        if clauses:
            q += " AND " + " AND ".join(clauses)
        q += " AND json_valid(value) ORDER BY key"
        if limit:
            q += f" LIMIT {int(limit)}"
        for key, value, md, blk, txn in self._conn.execute(q, args):
            yield key, VersionedValue(value, md, (blk, txn))

    def apply_updates(self, batch, savepoint):
        cur = self._conn.cursor()
        # meta_count == 0 ⇒ no existing row carries metadata, so the
        # per-key decrement probe is skippable (keeps the common
        # no-SBE channel free of per-write SELECTs)
        track = self.meta_count > 0
        if (not track and not batch.has_meta
                and isinstance(batch, ColumnarUpdateBatch)):
            # columnar fast path: one executemany per namespace over
            # the validator's slabs — no dict materialization, no
            # VersionedValue churn, zero-copy value blobs
            for dels, rows in batch.sqlite_columns():
                if dels:
                    cur.executemany(
                        "DELETE FROM state WHERE ns=? AND key=?", dels
                    )
                if rows:
                    cur.executemany(
                        "INSERT OR REPLACE INTO state VALUES (?,?,?,?,?,?)",
                        rows,
                    )
            items = batch.extra_items()
        else:
            items = batch.items()
        for (ns, key), vv in items:
            if track:
                row = cur.execute(
                    "SELECT metadata FROM state WHERE ns=? AND key=?",
                    (ns, key),
                ).fetchone()
                if row is not None and row[0]:
                    self.meta_count -= 1
            if vv.value is None:
                cur.execute("DELETE FROM state WHERE ns=? AND key=?", (ns, key))
            else:
                if vv.metadata:
                    self.meta_count += 1
                cur.execute(
                    "INSERT OR REPLACE INTO state VALUES (?,?,?,?,?,?)",
                    (ns, key, vv.value, vv.metadata, vv.version[0], vv.version[1]),
                )
        if savepoint is not None:
            cur.execute(
                "INSERT OR REPLACE INTO savepoint VALUES (0,?,?)",
                (savepoint[0], savepoint[1]),
            )
        self._conn.commit()

    def savepoint(self):
        row = self._conn.execute(
            "SELECT block, txnum FROM savepoint WHERE id=0"
        ).fetchone()
        return (row[0], row[1]) if row else None

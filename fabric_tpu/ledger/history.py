"""History database: key → commit positions (analog
core/ledger/kvledger/history — GetHistoryForKey support)."""

from __future__ import annotations

import sqlite3


class HistoryDB:
    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        # history is DERIVED state: the kvledger recovery path replays
        # it from stored blocks (savepoint-gated), so a lost WAL tail
        # on crash self-heals — no per-commit fsync.  NORMAL, not OFF:
        # OFF can corrupt the DB file itself on power loss, and there
        # is no drop-and-rebuild path on open
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS hist ("
            " ns TEXT, key TEXT, block INTEGER, txnum INTEGER,"
            " PRIMARY KEY (ns, key, block, txnum))"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS savepoint ("
            " id INTEGER PRIMARY KEY CHECK (id = 0), block INTEGER)"
        )

    def commit_block(self, block_num: int, writes: list[tuple[str, str, int]]):
        """writes: [(ns, key, txnum)] for VALID txs of the block."""
        self._conn.executemany(
            "INSERT OR REPLACE INTO hist VALUES (?,?,?,?)",
            [(ns, key, block_num, txnum) for ns, key, txnum in writes],
        )
        self._conn.execute(
            "INSERT OR REPLACE INTO savepoint VALUES (0,?)", (block_num,)
        )
        self._conn.commit()

    def get_history_for_key(self, ns: str, key: str):
        """Yield (block, txnum) newest-first (like the reference's
        history iterator)."""
        yield from self._conn.execute(
            "SELECT block, txnum FROM hist WHERE ns=? AND key=?"
            " ORDER BY block DESC, txnum DESC",
            (ns, key),
        )

    def savepoint(self) -> int | None:
        row = self._conn.execute("SELECT block FROM savepoint WHERE id=0").fetchone()
        return row[0] if row else None

    def close(self):
        self._conn.close()

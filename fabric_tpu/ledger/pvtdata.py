"""Private-data store: cleartext collection write-sets per block.

Analog of core/ledger/pvtdatastorage/store.go: pvt write-sets keyed
(block, tx, namespace, collection) with block-to-live (BTL) expiry and
missing-data bookkeeping for the reconciler (gossip/privdata).
"""

from __future__ import annotations

import json
import sqlite3


def encode_kv(kv: dict) -> bytes:
    """{key: value|None} → canonical stored/wire JSON bytes (hex
    values) — THE pvt cleartext encoding, shared by the pvtdata store
    payloads, gossip push/pull, and the reconciler."""
    return json.dumps(
        {k: (v.hex() if v is not None else None) for k, v in kv.items()},
        sort_keys=True,
    ).encode()


def decode_kv(raw) -> dict:
    data = json.loads(raw)
    return {k: (bytes.fromhex(v) if v is not None else None)
            for k, v in data.items()}


class PvtDataStore:
    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS pvt ("
            " block INTEGER, txnum INTEGER, ns TEXT, coll TEXT, rwset BLOB,"
            " expiry INTEGER DEFAULT 0,"  # 0 = never (btl unset)
            " PRIMARY KEY (block, txnum, ns, coll))"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS missing ("
            " block INTEGER, txnum INTEGER, ns TEXT, coll TEXT, eligible INTEGER,"
            " PRIMARY KEY (block, txnum, ns, coll))"
        )
        # purge_expired runs on EVERY commit: without this partial
        # index it would table-scan rows that mostly have expiry=0
        self._conn.execute(
            "CREATE INDEX IF NOT EXISTS pvt_expiry ON pvt(expiry)"
            " WHERE expiry > 0"
        )

    def commit_block(self, block_num: int, data: dict, missing: list | None = None):
        """data: {(txnum, ns, coll): (rwset_bytes, expiry_block)} —
        expiry_block 0 = no BTL.  missing: [(txnum, ns, coll, eligible)]."""
        cur = self._conn.cursor()
        for (txnum, ns, coll), val in data.items():
            rwset, expiry = val if isinstance(val, tuple) else (val, 0)
            cur.execute(
                "INSERT OR REPLACE INTO pvt VALUES (?,?,?,?,?,?)",
                (block_num, txnum, ns, coll, rwset, expiry),
            )
        for txnum, ns, coll, eligible in missing or ():
            cur.execute(
                "INSERT OR REPLACE INTO missing VALUES (?,?,?,?,?)",
                (block_num, txnum, ns, coll, int(eligible)),
            )
        self._conn.commit()

    def get_pvt_data(self, block_num: int) -> dict:
        out = {}
        for txnum, ns, coll, rwset in self._conn.execute(
            "SELECT txnum, ns, coll, rwset FROM pvt WHERE block=?", (block_num,)
        ):
            out[(txnum, ns, coll)] = rwset
        return out

    def missing_data(self, max_block: int, eligible_only: bool = True):
        q = "SELECT block, txnum, ns, coll FROM missing WHERE block<=?"
        if eligible_only:
            q += " AND eligible=1"
        return list(self._conn.execute(q, (max_block,)))

    def resolve_missing(self, block: int, txnum: int, ns: str, coll: str, rwset: bytes, expiry: int = 0):
        """Reconciler delivered previously missing data."""
        cur = self._conn.cursor()
        cur.execute(
            "INSERT OR REPLACE INTO pvt VALUES (?,?,?,?,?,?)",
            (block, txnum, ns, coll, rwset, expiry),
        )
        cur.execute(
            "DELETE FROM missing WHERE block=? AND txnum=? AND ns=? AND coll=?",
            (block, txnum, ns, coll),
        )
        self._conn.commit()

    def purge_expired(self, current_block: int) -> list:
        """BTL expiry (analog pvtstatepurgemgmt): drop pvt data whose
        expiry block has passed.  Returns the purged rows
        [(block, txnum, ns, coll, rwset)] so the ledger can also erase
        the corresponding private STATE (cleartext + key-hash spaces)."""
        rows = list(self._conn.execute(
            "SELECT block, txnum, ns, coll, rwset FROM pvt"
            " WHERE expiry > 0 AND expiry <= ?", (current_block,)
        ))
        if rows:
            self._conn.execute(
                "DELETE FROM pvt WHERE expiry > 0 AND expiry <= ?",
                (current_block,),
            )
            self._conn.commit()
        return rows

    def close(self):
        self._conn.close()

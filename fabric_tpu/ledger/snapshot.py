"""Ledger snapshots: export, verify, and join-channel-from-snapshot.

Reference: kvledger/snapshot.go — ``generateSnapshot`` (:93) exports
public state + committed txids + signable metadata with per-file
hashes; ``CreateFromSnapshot`` (:222) bootstraps a brand-new peer's
ledger at the snapshot height, with the block store positioned so the
next delivered block continues the chain (and dup-txid checks covering
pre-snapshot history).  The snapshot also carries the channel's last
CONFIG so the joining peer derives its trust anchor from material the
admin hands over — exactly like joining from a genesis block.

File format: length-prefixed records (not sqlite dumps) so snapshots
are portable across state-DB backends; every file is SHA-256 hashed
into _snapshot_signable_metadata.json (the reference's tamper-evidence
contract).
"""

from __future__ import annotations

import hashlib
import json
import os
import struct

from fabric_tpu.ledger.statedb import UpdateBatch, VersionedValue

_LEN = struct.Struct("<I")

STATE_FILE = "public_state.data"
TXIDS_FILE = "txids.data"
META_FILE = "_snapshot_signable_metadata.json"


class _HashingWriter:
    def __init__(self, path: str):
        self.f = open(path, "wb")
        self.h = hashlib.sha256()

    def record(self, *fields: bytes):
        for b in fields:
            hdr = _LEN.pack(len(b))
            self.f.write(hdr)
            self.f.write(b)
            self.h.update(hdr)
            self.h.update(b)

    def close(self) -> str:
        self.f.close()
        return self.h.hexdigest()


def _iter_records(path: str, arity: int):
    with open(path, "rb") as f:
        while True:
            hdr = f.read(4)
            if not hdr:
                return
            fields = []
            for i in range(arity):
                if i:
                    hdr = f.read(4)
                (n,) = _LEN.unpack(hdr)
                fields.append(f.read(n))
            yield tuple(fields)


def _file_hash(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def generate_snapshot(ledger, out_dir: str, channel_id: str = "",
                      config_bytes: bytes = b"") -> dict:
    """Export ``ledger`` (fabric_tpu.ledger.kvledger.KVLedger) at its
    current height.  Returns the signable metadata dict.

    The caller serializes this against commits (the peer takes the
    channel commit lock — snapshot_mgmt.go's commitStart/commitDone
    interlock)."""
    os.makedirs(out_dir, exist_ok=True)
    # async group-commit engine (PR 17): queued state applies may
    # still trail the block append — drain them so the exported state
    # is exactly the boundary state at ``height``
    drain = getattr(ledger, "drain_state", None)
    if drain is not None:
        drain()
    height = ledger.blocks.height
    if height == 0:
        raise ValueError("cannot snapshot an empty ledger")
    last = ledger.blocks.get_block(height - 1)
    from fabric_tpu import protoutil

    if last is not None:
        last_hash = protoutil.block_header_hash(last.header).hex()
        prev_hash = last.header.previous_hash.hex()
    else:
        # snapshot-joined peer re-exporting before any new commit: the
        # anchors persist in the block store's bootstrap record
        boot = ledger.blocks.bootstrap_info()
        if boot is None:
            raise ValueError("empty store without bootstrap anchor")
        last_hash = boot[1].hex()
        prev_hash = ""

    sw = _HashingWriter(os.path.join(out_dir, STATE_FILE))
    for (ns, key), vv in ledger.state.iter_all():
        # public + hashed-collection state only: pvt CLEARTEXT
        # (ns$coll) is per-peer confidential material and would make
        # the snapshot hash peer-dependent; joined peers re-acquire
        # pvt data via reconciliation, like the reference
        if "$" in ns and not ns.endswith("#hashed"):
            continue
        sw.record(
            ns.encode(), key.encode(), vv.value or b"",
            _LEN.pack(vv.version[0]) + _LEN.pack(vv.version[1]),
            vv.metadata or b"",
        )
    state_hash = sw.close()

    tw = _HashingWriter(os.path.join(out_dir, TXIDS_FILE))
    for txid, code in ledger.blocks.iter_txid_codes():
        tw.record(txid.encode(), bytes([code & 0xFF]))
    txids_hash = tw.close()

    sp = ledger.state.savepoint()
    meta = {
        "channel_name": channel_id,
        "last_block_number": height - 1,
        "last_block_hash": last_hash,
        "previous_block_hash": prev_hash,
        "last_commit_hash": (ledger.commit_hash or b"").hex(),
        # the catch-up contract (peer/replay.py): ``height`` is where
        # replay takes over (blocks < height are inside the snapshot),
        # ``state_savepoint`` pins the state DB's recovery anchor so
        # the importer's reconcile-on-open sees a consistent pair
        "height": height,
        "state_savepoint": (list(sp) if sp is not None else None),
        "config": config_bytes.hex(),
        "files": {STATE_FILE: state_hash, TXIDS_FILE: txids_hash},
    }
    with open(os.path.join(out_dir, META_FILE), "w") as f:
        json.dump(meta, f, sort_keys=True, indent=1)
    return meta


def verify_snapshot(snap_dir: str) -> dict:
    """Check every file hash against the signable metadata; returns the
    metadata (kvledger/snapshot.go:368 verification)."""
    with open(os.path.join(snap_dir, META_FILE)) as f:
        meta = json.load(f)
    for name, want in meta["files"].items():
        got = _file_hash(os.path.join(snap_dir, name))
        if got != want:
            raise ValueError(f"snapshot file {name} hash mismatch")
    return meta


def create_from_snapshot(snap_dir: str, ledger_dir: str, state_db=None,
                         enable_history: bool = True,
                         async_commit: bool = False,
                         apply_queue_blocks: int = 4):
    """Build a fresh KVLedger positioned at the snapshot boundary
    (CreateFromSnapshot, kvledger/snapshot.go:222).

    Returns (ledger, meta).  History prior to the snapshot is absent by
    design (the reference's from-snapshot peers serve no pre-snapshot
    history either)."""
    from fabric_tpu.ledger.kvledger import KVLedger

    meta = verify_snapshot(snap_dir)
    lg = KVLedger(ledger_dir, state_db=state_db, enable_history=enable_history,
                  async_commit=async_commit,
                  apply_queue_blocks=apply_queue_blocks)
    if lg.blocks.height != 0:
        raise ValueError("ledger directory is not empty")

    batch = UpdateBatch()
    n = 0
    last_block = meta["last_block_number"]
    # exported savepoint (absent in pre-height snapshots): the
    # importer reproduces the EXACT recovery anchor the exporter
    # held, so savepoint/height reconciliation on reopen is the
    # identity, under both the serial and async commit engines
    sp = tuple(meta.get("state_savepoint") or (last_block, 0))
    for ns, key, value, ver, md in _iter_records(
        os.path.join(snap_dir, STATE_FILE), 5
    ):
        blk, txn = _LEN.unpack(ver[:4])[0], _LEN.unpack(ver[4:])[0]
        batch.put(ns.decode(), key.decode(), value, (blk, txn), md or None)
        n += 1
        if n % 10000 == 0:
            lg.state.apply_updates(batch, sp)
            batch = UpdateBatch()
    lg.state.apply_updates(batch, sp)

    lg.blocks.bootstrap_from_snapshot(
        last_block + 1,
        bytes.fromhex(meta["last_block_hash"]),
        ((t.decode(), c[0]) for (t, c) in _iter_records(
            os.path.join(snap_dir, TXIDS_FILE), 2
        )),
        commit_hash=bytes.fromhex(meta["last_commit_hash"]),
    )
    lg.bootstrap_commit_hash(bytes.fromhex(meta["last_commit_hash"]) or None)
    return lg, meta


def iter_state_records(snap_dir: str):
    """Decoded ``(ns, key, value, (block, txnum), metadata)`` stream
    off a snapshot's state file — the warm/inspection reader."""
    for ns, key, value, ver, md in _iter_records(
        os.path.join(snap_dir, STATE_FILE), 5
    ):
        yield (
            ns.decode(), key.decode(), value,
            (_LEN.unpack(ver[:4])[0], _LEN.unpack(ver[4:])[0]),
            md or None,
        )


def warm_resident(res, snap_dir: str, limit: int | None = None) -> int:
    """Warm the device-resident MVCC cache (state/residency.py)
    straight from a snapshot's key ranges — the snapshot-join peer
    skips the fault-in-miss-by-miss phase entirely: every key the
    import just wrote to the state DB lands in the device table as a
    committed (present, version) row before the first replayed block
    launches.  Values stay host-side (the cache holds version rows);
    pvt cleartext was never exported.  Returns keys admitted (0 when
    the cache is absent/disabled or the warm stops at capacity)."""
    if res is None or not res.enabled:
        return 0
    return res.warm(
        ((ns, key, ver) for ns, key, _v, ver, _m in
         iter_state_records(snap_dir)),
        limit=limit,
    )


def state_digest(state) -> str:
    """Order-insensitive content hash over a state DB's committed
    ``(ns, key, value, version, metadata)`` records — the
    byte-identity oracle the snapshot/replay differential tests pin:
    a snapshot-then-replay join must produce EXACTLY the state a
    replay from genesis produces.

    Each record is hashed in the snapshot's own framing and the
    per-record digests are XOR-combined, so backends that iterate in
    different orders (and ledgers whose histories applied the same
    writes through different batch boundaries) compare equal iff
    their committed records are byte-identical."""
    acc = bytearray(32)
    for (ns, key), vv in state.iter_all():
        h = hashlib.sha256()
        for b in (
            ns.encode(), key.encode(), vv.value or b"",
            _LEN.pack(vv.version[0]) + _LEN.pack(vv.version[1]),
            vv.metadata or b"",
        ):
            h.update(_LEN.pack(len(b)))
            h.update(b)
        d = h.digest()
        for i in range(32):
            acc[i] ^= d[i]
    return bytes(acc).hex()

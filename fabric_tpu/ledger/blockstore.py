"""Append-only block store with sqlite index and crash recovery.

Analog of the reference's block storage
(common/ledger/blkstorage/blockfile_mgr.go:281 addBlock; index
blockindex.go).  Blocks are length-prefixed protobufs in numbered
segment files; a sqlite index maps number/hash/txid → (file, offset).
On open, a partially written tail record (crash mid-append) is
truncated — the reference's atomic-write recovery — and the index is
rebuilt forward from the last indexed block, so the FILES are the
source of truth and the index is derived state.
"""

from __future__ import annotations

import os
import sqlite3
import struct
import threading

from google.protobuf.message import DecodeError

from fabric_tpu import faults as _faults
from fabric_tpu import protoutil
from fabric_tpu.protos import common_pb2

_SEGMENT_MAX = 64 * 1024 * 1024
_LEN = struct.Struct("<I")


class BlockStore:
    def __init__(self, dirpath: str, group_commit: int = 8,
                 group_max_lag_s: float = 0.5):
        """``group_commit``: fsync the segment file every N blocks
        instead of every block (1 = always).  Safe because the commit
        path is replay-recoverable end to end: a crash inside the
        window loses only the unsynced TAIL of the segment file, which
        _recover truncates; the peer's deliver loop then re-fetches
        those blocks from the ordering service and state/history catch
        up through the normal replay path (kv_ledger.go:357 recoverDBs
        analog) — no committed-and-acknowledged data is at risk
        because downstream acknowledgment (gateway commit status)
        keys off the block store height after recovery.
        ``group_max_lag_s`` bounds the window WHILE TRAFFIC FLOWS (the
        check runs at the next add_block); a burst followed by silence
        is closed by callers of ``sync()`` — the peer forces it before
        acknowledging commit status (node.py commit_block), and
        close() always syncs."""
        self.dir = dirpath
        self.group_commit = max(1, int(group_commit))
        self.group_max_lag_s = group_max_lag_s
        self._unsynced = 0
        self._oldest_unsynced: float | None = None
        self._fsync_ctr = None  # lazy blockstore_fsync_total counter
        # serializes segment-file writes/fsyncs between the committer
        # thread (add_block) and the async engine's applier thread
        # (ensure_synced — the durability fence); uncontended cost is
        # one futex op per block
        self._io_lock = threading.Lock()
        os.makedirs(dirpath, exist_ok=True)
        self._idx = sqlite3.connect(
            os.path.join(dirpath, "index.db"), check_same_thread=False
        )
        self._idx.execute("PRAGMA journal_mode=WAL")
        # the index is DERIVED state (rebuilt forward — and clamped
        # backward — from the segment files by _recover), so commits
        # need no fsync; NORMAL (not OFF) keeps the WAL checkpoint
        # itself crash-safe — OFF can corrupt the main DB file on
        # power loss, and there is no drop-and-rebuild path
        self._idx.execute("PRAGMA synchronous=NORMAL")
        self._idx.execute(
            "CREATE TABLE IF NOT EXISTS blocks ("
            " num INTEGER PRIMARY KEY, hash BLOB, seg INTEGER, off INTEGER)"
        )
        self._idx.execute(
            "CREATE TABLE IF NOT EXISTS txids ("
            " txid TEXT PRIMARY KEY, num INTEGER, txnum INTEGER, code INTEGER)"
        )
        self._idx.execute(
            "CREATE INDEX IF NOT EXISTS blocks_hash ON blocks(hash)"
        )
        self._idx.execute(
            "CREATE TABLE IF NOT EXISTS bootstrap ("
            " id INTEGER PRIMARY KEY CHECK (id = 0),"
            " first_block INTEGER, prev_hash BLOB, commit_hash BLOB)"
        )
        self._recover()
        # fsync watermark in block numbers: everything recovery left in
        # the files is already durable (or was truncated away), so the
        # synced watermark starts at the tip
        self._last_appended = self.height - 1
        self._synced_num = self._last_appended

    # -- segment file plumbing --------------------------------------------

    def _seg_path(self, seg: int) -> str:
        return os.path.join(self.dir, f"blocks_{seg:06d}.bin")

    def _segments(self) -> list[int]:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("blocks_") and name.endswith(".bin"):
                out.append(int(name[7:13]))
        return sorted(out)

    def _recover(self) -> None:
        segs = self._segments()
        if not segs:
            self._seg = 0
            self._fh = open(self._seg_path(0), "ab")
            return
        # truncate torn tail record of the last segment
        last = segs[-1]
        path = self._seg_path(last)
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            off = 0
            while off + _LEN.size <= size:
                (n,) = _LEN.unpack(f.read(_LEN.size))
                if off + _LEN.size + n > size:
                    break
                f.seek(n, 1)
                off += _LEN.size + n
        if off < size:
            with open(path, "ab") as f:
                f.truncate(off)
        # re-index anything beyond the last indexed block
        row = self._idx.execute("SELECT MAX(num) FROM blocks").fetchone()
        next_num = (row[0] + 1) if row[0] is not None else 0
        file_max = -1
        for seg in segs:
            for block, offset in self._scan(seg):
                file_max = max(file_max, block.header.number)
                if block.header.number >= next_num:
                    self._index_block(block, seg, offset)
        # clamp the index BACK to the files: group commit means the
        # sqlite index (WAL) can be durably ahead of an unsynced
        # segment tail a crash truncated — the FILES are the source of
        # truth in both directions
        if next_num - 1 > file_max:
            self._idx.execute(
                "DELETE FROM blocks WHERE num > ?", (file_max,)
            )
            self._idx.execute(
                "DELETE FROM txids WHERE num > ?", (file_max,)
            )
        self._idx.commit()
        self._seg = last
        self._fh = open(path, "ab")

    def _scan(self, seg: int):
        path = self._seg_path(seg)
        with open(path, "rb") as f:
            off = 0
            while True:
                hdr = f.read(_LEN.size)
                if len(hdr) < _LEN.size:
                    return
                (n,) = _LEN.unpack(hdr)
                data = f.read(n)
                if len(data) < n:
                    return
                block = common_pb2.Block()
                block.ParseFromString(data)
                yield block, off
                off += _LEN.size + n

    # -- index -------------------------------------------------------------

    def _index_block(
        self, block: common_pb2.Block, seg: int, off: int, txids=None
    ) -> None:
        """txids: optional pre-parsed [(txid, tx_num)] — the commit
        path already holds the parsed envelopes, so re-unmarshalling
        every envelope here (3 protobuf parses per tx) is skipped."""
        self._idx.execute(
            "INSERT OR REPLACE INTO blocks VALUES (?,?,?,?)",
            (block.header.number, protoutil.block_header_hash(block.header), seg, off),
        )
        flags = protoutil.get_tx_filter(block)
        if txids is None:
            txids = []
            for i, env_bytes in enumerate(block.data.data):
                try:
                    env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
                    payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
                    ch = protoutil.unmarshal(
                        common_pb2.ChannelHeader, payload.header.channel_header
                    )
                except DecodeError:
                    continue  # non-envelope payload: nothing to index
                if ch.tx_id:
                    txids.append((ch.tx_id, i))
        self._idx.executemany(
            "INSERT OR IGNORE INTO txids VALUES (?,?,?,?)",
            [
                (txid, block.header.number, i,
                 flags[i] if i < len(flags) else 254)
                for txid, i in txids if txid
            ],
        )

    # -- public API --------------------------------------------------------

    @property
    def unsynced(self) -> int:
        """Blocks appended since the last fsync — the open group-commit
        window's depth (0 = everything durable)."""
        return self._unsynced

    def _count_fsync(self, trigger: str) -> None:
        """``blockstore_fsync_total{trigger}``: how each fsync window
        closed — ``group`` (window filled), ``lag`` (max-lag bound),
        ``forced`` (explicit sync(): barrier/tail/ack/close).  Under
        the deep-pipelined committer's deferred syncs this is the
        number that shows the fsync batching actually engaging."""
        ctr = self._fsync_ctr
        if ctr is None:
            from fabric_tpu.ops_metrics import global_registry

            ctr = self._fsync_ctr = global_registry().counter(
                "blockstore_fsync_total",
                "segment fsyncs by closing trigger",
            )
        ctr.add(1, trigger=trigger)

    @property
    def height(self) -> int:
        row = self._idx.execute("SELECT MAX(num) FROM blocks").fetchone()
        if row[0] is not None:
            return row[0] + 1
        boot = self._idx.execute(
            "SELECT first_block FROM bootstrap WHERE id=0"
        ).fetchone()
        return boot[0] if boot else 0

    def bootstrap_from_snapshot(self, first_block: int, prev_hash: bytes,
                                txid_codes, commit_hash: bytes = b"") -> None:
        """Position an EMPTY store at a snapshot boundary: height
        becomes ``first_block``, the snapshot's committed txids (WITH
        their original validation codes) join the dup-check index, and
        the chain/commit-hash anchors persist for reopen + continuity
        checks (blkstorage bootstrapping snapshot,
        kvledger/snapshot.go:222 CreateFromSnapshot)."""
        if self.height != 0:
            raise ValueError("bootstrap requires an empty block store")
        self._idx.execute(
            "INSERT OR REPLACE INTO bootstrap VALUES (0, ?, ?, ?)",
            (first_block, prev_hash, commit_hash),
        )
        self._idx.executemany(
            "INSERT OR IGNORE INTO txids VALUES (?,?,?,?)",
            ((t, -1, -1, c) for t, c in txid_codes),
        )
        self._idx.commit()

    def bootstrap_info(self):
        """→ (first_block, prev_hash, commit_hash) or None."""
        boot = self._idx.execute(
            "SELECT first_block, prev_hash, commit_hash FROM bootstrap WHERE id=0"
        ).fetchone()
        return tuple(boot) if boot else None

    def iter_txids(self):
        """All committed txids in sorted order (snapshot export)."""
        for (t,) in self._idx.execute("SELECT txid FROM txids ORDER BY txid"):
            yield t

    def iter_txid_codes(self):
        """(txid, validation_code) in sorted order — codes survive the
        snapshot so a joined peer's tx-status queries stay truthful."""
        for t, c in self._idx.execute(
            "SELECT txid, code FROM txids ORDER BY txid"
        ):
            yield t, int(c)

    def expected_prev_hash(self) -> bytes | None:
        """Hash the next block's previous_hash must carry, when known
        (last stored block, or the snapshot anchor).  Cached in memory
        after the first lookup — this sits on the commit hot path."""
        cached = getattr(self, "_last_hash", None)
        if cached is not None:
            return cached
        row = self._idx.execute("SELECT MAX(num) FROM blocks").fetchone()
        if row[0] is not None:
            self._last_hash = self._idx.execute(
                "SELECT hash FROM blocks WHERE num=?", (row[0],)
            ).fetchone()[0]
            return self._last_hash
        boot = self.bootstrap_info()
        return boot[1] if boot else None

    def add_block(self, block: common_pb2.Block, txids=None,
                  hd_bytes: bytes | None = None) -> None:
        """``hd_bytes``: optional pre-serialized header+data fields
        (protoutil.block_header_data_bytes, built off the commit
        thread) — metadata is spliced on here so the committer never
        re-serializes the envelopes."""
        if block.header.number != self.height:
            raise ValueError(
                f"block number {block.header.number} != height {self.height}"
            )
        want_prev = self.expected_prev_hash()
        if want_prev and block.header.previous_hash != want_prev:
            raise ValueError(
                f"block {block.header.number} previous_hash does not "
                "extend this chain"
            )
        if hd_bytes is not None:
            data = protoutil.append_block_metadata(hd_bytes, block)
        else:
            data = block.SerializeToString()
        import time as _time

        with self._io_lock:
            if (self._fh.tell() + len(data) > _SEGMENT_MAX
                    and self._fh.tell() > 0):
                # a finished segment must be durable
                self._sync_locked("forced")
                self._fh.close()
                self._seg += 1
                self._fh = open(self._seg_path(self._seg), "ab")
            off = self._fh.tell()
            self._fh.write(_LEN.pack(len(data)))
            self._fh.write(data)
            self._fh.flush()
            self._last_appended = block.header.number
            # group commit: amortize the fsync over a window of blocks
            # (see __init__ for the replay-safety argument)
            self._unsynced += 1
            if self._oldest_unsynced is None:
                self._oldest_unsynced = _time.monotonic()
            if (
                self._unsynced >= self.group_commit
                or _time.monotonic() - self._oldest_unsynced
                >= self.group_max_lag_s
            ):
                # crash-consistency hooks: the kill-mid-fsync chaos
                # tests exit the process inside _sync_locked (before =
                # the whole window is lost and _recover must truncate
                # the torn tail; after = the window is durable) and
                # assert replay to a consistent height on reopen
                self._sync_locked(
                    "group" if self._unsynced >= self.group_commit
                    else "lag"
                )
        self._index_block(block, self._seg, off, txids=txids)
        self._idx.commit()
        self._last_hash = protoutil.block_header_hash(block.header)

    def _read_at(self, seg: int, off: int) -> common_pb2.Block | None:
        try:
            with open(self._seg_path(seg), "rb") as f:
                f.seek(off)
                (n,) = _LEN.unpack(f.read(_LEN.size))
                block = common_pb2.Block()
                block.ParseFromString(f.read(n))
                return block
        except (OSError, struct.error):
            return None

    def get_block(self, number: int) -> common_pb2.Block | None:
        row = self._idx.execute(
            "SELECT seg, off FROM blocks WHERE num=?", (number,)
        ).fetchone()
        return self._read_at(*row) if row else None

    def get_block_by_hash(self, h: bytes) -> common_pb2.Block | None:
        row = self._idx.execute(
            "SELECT seg, off FROM blocks WHERE hash=?", (h,)
        ).fetchone()
        return self._read_at(*row) if row else None

    def get_tx_loc(self, txid: str):
        """→ (block_num, tx_num, validation_code) or None (dup-txid
        check + qscc GetTransactionByID)."""
        row = self._idx.execute(
            "SELECT num, txnum, code FROM txids WHERE txid=?", (txid,)
        ).fetchone()
        return tuple(row) if row else None

    def tx_exists(self, txid: str) -> bool:
        return self.get_tx_loc(txid) is not None

    def iter_blocks(self, start: int = 0):
        num = start
        while True:
            blk = self.get_block(num)
            if blk is None:
                return
            yield blk
            num += 1

    def _sync_locked(self, trigger: str) -> None:
        # caller holds self._io_lock
        if self._unsynced:
            self._count_fsync(trigger)
            self._fh.flush()
            _faults.fire("ledger.fsync.before")
            os.fsync(self._fh.fileno())
            _faults.fire("ledger.fsync.after")
            self._unsynced = 0
            self._oldest_unsynced = None
        self._synced_num = self._last_appended

    def sync(self) -> None:
        """Force-fsync any group-commit window still open."""
        with self._io_lock:
            self._sync_locked("forced")

    @property
    def synced_height(self) -> int:
        """Highest block number known durable + 1 (mirrors ``height``
        for the appended side) — the commit-engine postmortem reads
        appended vs synced vs applied off these watermarks."""
        return self._synced_num + 1

    def ensure_synced(self, num: int) -> None:
        """Durability fence: make every block up to ``num`` durable
        before returning.  The async apply engine's applier calls this
        in front of each state-DB apply so the durable savepoint can
        never get ahead of the block files; when the group-commit
        window already closed past ``num`` this is one lock op."""
        with self._io_lock:
            if num <= self._synced_num:
                return
            self._sync_locked("apply")

    def close(self):
        self.sync()
        self._fh.close()
        self._idx.close()

"""Asynchronous group-commit storage engine: state apply off the
block critical path.

Analog of the reference committer's split (core/ledger/kvledger
kvLedger.commit): the BLOCK-STORE append is the durability boundary —
a block is committed once it is in the chain files — while the
state-DB apply merely *trails* it and is reconstructible from those
files through the savepoint/replay machinery (recoverDBs,
kv_ledger.go:357).  Our serial engine paid the full SQLite apply on
the commit critical path anyway; :class:`AsyncApplyEngine` moves it to
an ordered background queue drained by one dedicated applier thread so
the host side of a committed block approaches pure dispatch: append +
enqueue.

The engine is itself a :class:`~fabric_tpu.ledger.statedb.VersionedDB`
wrapping the real backend, which is what makes the move safe:

* **ordering** — one FIFO queue, one applier: batches land in commit
  order, each under its own ``(block, 0)`` savepoint, exactly as the
  serial engine would have landed them;
* **read-your-writes** — every read (``get_state``, the bulk/column
  version gathers, range scans, rich queries) consults the pending
  overlay (newest batch first) in front of the inner DB, so MVCC
  preloads, lifecycle queries and the resident-cache commit scatter
  observe *identical* state to the synchronous engine — verdicts are
  bit-equal by construction, not by luck;
* **durability fence** — before applying block N against a *durable*
  backend the applier calls ``blocks.ensure_synced(N)``: the durable
  savepoint can never get ahead of the block files (the invariant the
  serial engine enforced with an inline ``sync()`` per commit — moved
  here, it also pulls those per-commit fsyncs off the critical path);
* **backpressure** — the queue is bounded in BLOCKS; ``submit`` parks
  the committer at the block boundary until the applier catches up, so
  lag is never unbounded and crash-recovery replay stays short;
* **crash recovery** — a crash loses at most the queued tail; on
  reopen the state savepoint trails the block height and
  ``KVLedger.recover`` replays the gap from the chain files.  The
  ``ledger.apply.before``/``ledger.apply.after`` fault points let the
  differential battery kill the applier at every queue depth.

A failed apply latches: the applier stops (ordered apply cannot skip),
and the error re-raises at the next ``submit``/``drain`` — fail-stop,
never fail-skip.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from fabric_tpu import faults as _faults
from fabric_tpu.ledger.statedb import VersionedDB
from fabric_tpu.observe import txflow as _txflow

_log = logging.getLogger("fabric_tpu.ledger.committer")


class _Pending:
    """One queued block apply."""

    __slots__ = ("num", "batch", "sp", "post_apply", "enqueued_at")

    def __init__(self, num, batch, sp, post_apply, enqueued_at):
        self.num = num
        self.batch = batch
        self.sp = sp
        self.post_apply = post_apply
        self.enqueued_at = enqueued_at


def _merge_overlay(inner_iter, ov: dict):
    """Merge a sorted ``(key, VersionedValue)`` iterator with an
    overlay dict ``{key: VersionedValue | None}`` (None = the overlay
    suppresses the row: a pending delete, or a pending rewrite that no
    longer matches the caller's predicate).  Overlay wins on key
    collision; output stays in key order."""
    ks = sorted(ov)
    i, n = 0, len(ks)
    for key, vv in inner_iter:
        while i < n and ks[i] < key:
            o = ov[ks[i]]
            if o is not None:
                yield ks[i], o
            i += 1
        if i < n and ks[i] == key:
            o = ov[ks[i]]
            i += 1
            if o is not None:
                yield key, o
        else:
            yield key, vv
    while i < n:
        o = ov[ks[i]]
        if o is not None:
            yield ks[i], o
        i += 1


class AsyncApplyEngine(VersionedDB):
    """Ordered background applier in front of a real VersionedDB.

    The inner backend must already be open; ``close()`` drains the
    queue, joins the applier and closes the inner DB.  The applier
    thread starts lazily on the first ``submit`` so idle ledgers
    (tests open hundreds) never park a thread.
    """

    def __init__(self, inner: VersionedDB, blocks=None,
                 queue_blocks: int = 4, name: str = "state-applier"):
        self._inner = inner
        self._blocks = blocks  # durability fence (BlockStore), optional
        self._capacity = max(1, int(queue_blocks))
        self._name = name
        self._cond = threading.Condition()
        self._queue: deque[_Pending] = deque()
        self._thread: threading.Thread | None = None
        self._closing = False
        self._error: BaseException | None = None
        self._applied_num = -1
        self._applies_total = 0
        self._apply_s_total = 0.0
        self._backpressure_total = 0
        self._metrics = None  # lazy (gauge, hist, counter) bundle
        # mirrored so KVLedger's getattr(state, "durable") keeps working
        self.durable = getattr(inner, "durable", True)

    # -- write side --------------------------------------------------------

    def submit(self, num: int, batch, savepoint, post_apply=None) -> None:
        """Enqueue one block's batch for ordered background apply.
        Blocks at the block boundary while the queue is at capacity
        (the backpressure latch).  ``post_apply`` (optional, no-arg)
        runs on the applier thread after the batch lands — the
        history-DB commit rides here."""
        entry = _Pending(num, batch, savepoint, post_apply,
                         time.monotonic())
        with self._cond:
            self._raise_if_failed()
            waited = False
            while (len(self._queue) >= self._capacity
                   and self._error is None and not self._closing):
                waited = True
                self._cond.wait()
            self._raise_if_failed()
            if waited:
                self._backpressure_total += 1
            self._queue.append(entry)
            if self._thread is None:
                t = threading.Thread(target=self._apply_loop,
                                     name=f"fabtpu-{self._name}",
                                     daemon=True)
                self._thread = t
                t.start()
            self._cond.notify_all()

    def apply_updates(self, batch, savepoint) -> None:
        """VersionedDB SPI: enqueue, preserving order with every
        in-flight commit (recovery replay and the pvt BTL purge come
        through here)."""
        self.submit(savepoint[0] if savepoint else -1, batch, savepoint)

    def _raise_if_failed(self):
        # callers hold self._cond
        if self._error is not None:
            raise RuntimeError(
                "state applier failed; the apply queue is fail-stop"
            ) from self._error

    def _apply_loop(self):
        while True:
            with self._cond:
                while (not self._queue and not self._closing
                       and self._error is None):
                    self._cond.wait()
                if self._error is not None or (self._closing
                                               and not self._queue):
                    return
                entry = self._queue[0]  # stays queued: overlay serves it
            try:
                dur = self._apply_one(entry)
            except BaseException as e:  # latch: ordered apply can't skip
                _log.error("state apply of block %d failed: %s",
                           entry.num, e)
                with self._cond:
                    self._error = e
                    self._cond.notify_all()
                return
            with self._cond:
                # abort() may have dropped the queue mid-apply
                if self._queue and self._queue[0] is entry:
                    self._queue.popleft()
                self._applied_num = entry.num
                self._applies_total += 1
                self._apply_s_total += dur
                self._cond.notify_all()
            self._observe(dur)

    def _apply_one(self, entry: _Pending) -> float:
        _faults.fire("ledger.apply.before", block=entry.num)
        if self._blocks is not None and getattr(self._inner, "durable",
                                                True):
            # a DURABLE savepoint must never get ahead of the block
            # files (see module docstring) — fence before the apply
            self._blocks.ensure_synced(entry.num)
            _txflow.block_durable(entry.num)
        t0 = time.perf_counter()
        self._inner.apply_updates(entry.batch, entry.sp)
        if entry.post_apply is not None:
            entry.post_apply()
        dur = time.perf_counter() - t0
        # the decoupled path's visibility edge: the block's writes
        # (and history) became readable HERE, on the applier thread
        _txflow.block_applied(entry.num)
        _faults.fire("ledger.apply.after", block=entry.num)
        return dur

    # -- read side: pending overlay in front of the inner DB ---------------

    def _pending(self) -> list[_Pending]:
        with self._cond:
            return list(self._queue)

    def get_state(self, ns, key):
        for entry in reversed(self._pending()):
            vv = entry.batch.updates.get((ns, key))
            if vv is not None:
                return None if vv.value is None else vv
        return self._inner.get_state(ns, key)

    def get_versions_bulk(self, keys):
        pend = self._pending()
        if not pend:
            return self._inner.get_versions_bulk(keys)
        out, rest = {}, []
        for k in keys:
            for entry in reversed(pend):
                vv = entry.batch.updates.get(k)
                if vv is not None:
                    if vv.value is not None:
                        out[k] = vv.version
                    break
            else:
                rest.append(k)
        if rest:
            out.update(self._inner.get_versions_bulk(rest))
        return out

    def get_versions_cols(self, keys):
        present, vers = self._inner.get_versions_cols(keys)
        pend = self._pending()
        if pend:
            for i, k in enumerate(keys):
                for entry in reversed(pend):
                    vv = entry.batch.updates.get(k)
                    if vv is not None:
                        if vv.value is None:
                            present[i] = False
                            vers[i] = 0
                        else:
                            present[i] = True
                            vers[i] = vv.version
                        break
        return present, vers

    def _overlay_for(self, ns, pend, keep):
        """{key: vv-or-None} for every pending write in ``ns``;
        ``keep(vv)`` False maps to None (suppress the row)."""
        ov = {}
        for entry in pend:  # oldest → newest: newest wins
            for (n, k), vv in entry.batch.updates.items():
                if n == ns:
                    ov[k] = vv if keep(vv) else None
        return ov

    def get_state_range(self, ns, start, end, limit=0):
        pend = self._pending()
        if not pend:
            yield from self._inner.get_state_range(ns, start, end, limit)
            return
        ov = self._overlay_for(
            ns, pend,
            lambda vv: vv.value is not None,
        )
        ov = {k: v for k, v in ov.items()
              if k >= start and (not end or k < end)}
        # pending deletes/rewrites can drop at most len(ov) inner rows
        inner_limit = (limit + len(ov)) if limit else 0
        n = 0
        for key, vv in _merge_overlay(
                self._inner.get_state_range(ns, start, end, inner_limit),
                ov):
            yield key, vv
            n += 1
            if limit and n >= limit:
                return

    def execute_query(self, ns, query, limit=0):
        pend = self._pending()
        if not pend:
            yield from self._inner.execute_query(ns, query, limit)
            return
        import json

        sel = query.get("selector", {})

        def match(vv):
            if vv.value is None:
                return False
            try:
                doc = json.loads(vv.value)
            except (ValueError, UnicodeDecodeError):
                return False
            return all(doc.get(f) == want for f, want in sel.items())

        # a pending rewrite that no longer matches must SUPPRESS the
        # committed row (the inner DB would still match it)
        ov = self._overlay_for(ns, pend, match)
        inner_limit = (limit + len(ov)) if limit else 0
        n = 0
        for key, vv in _merge_overlay(
                self._inner.execute_query(ns, query, inner_limit), ov):
            yield key, vv
            n += 1
            if limit and n >= limit:
                return

    def iter_all(self):
        # snapshot export wants the WHOLE committed state: barrier
        self.drain()
        yield from self._inner.iter_all()

    def savepoint(self):
        with self._cond:
            for entry in reversed(self._queue):
                if entry.sp is not None:
                    return entry.sp
        return self._inner.savepoint()

    @property
    def meta_count(self):
        """SBE gate: conservative — a pending batch carrying metadata
        counts before the inner DB has seen it."""
        with self._cond:
            pend = sum(1 for e in self._queue
                       if getattr(e.batch, "has_meta", False))
        return self._inner.meta_count + pend

    # -- lifecycle / introspection -----------------------------------------

    def drain(self) -> None:
        """Barrier: block until every queued batch has applied; raises
        if the applier latched a failure."""
        with self._cond:
            while self._queue and self._error is None:
                self._cond.wait(0.5)
            self._raise_if_failed()

    def wait_applied(self, num: int, timeout: float = 30.0) -> bool:
        """Block until block ``num`` has applied (or timeout)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while (self._applied_num < num and self._error is None
                   and time.monotonic() < deadline):
                self._cond.wait(0.2)
            self._raise_if_failed()
            return self._applied_num >= num

    def stats(self) -> dict:
        """Queue telemetry for /vitals, bench extras, the autopilot's
        apply-age signal and the blackbox postmortem."""
        with self._cond:
            depth = len(self._queue)
            oldest = self._queue[0].enqueued_at if self._queue else None
            out = {
                "queue_depth": depth,
                "queue_capacity": self._capacity,
                "oldest_age_ms": ((time.monotonic() - oldest) * 1000.0
                                  if oldest is not None else 0.0),
                "applied_num": self._applied_num,
                "applies_total": self._applies_total,
                "apply_ms_total": self._apply_s_total * 1000.0,
                "backpressure_total": self._backpressure_total,
                "failed": self._error is not None,
            }
        return out

    def _observe(self, dur: float) -> None:
        m = self._metrics
        if m is None:
            from fabric_tpu.ops_metrics import global_registry

            reg = global_registry()
            m = self._metrics = (
                reg.gauge("commit_apply_queue_depth",
                          "pending state-apply batches"),
                reg.histogram("commit_state_apply_seconds",
                              "background state-DB apply per block"),
                reg.counter("commit_state_applies_total",
                            "state batches applied in the background"),
            )
        gauge, hist, ctr = m
        with self._cond:
            gauge.set(float(len(self._queue)))
        hist.observe(dur)
        ctr.add(1)

    def abort(self) -> None:
        """Crash-simulation seam for the differential battery: DROP the
        pending queue without applying, stop the applier and close the
        inner DB — the state the process would leave behind had it
        died mid-queue.  Never called on a live peer."""
        with self._cond:
            self._queue.clear()
            self._closing = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._inner.close()

    def close(self) -> None:
        abandoned = 0
        with self._cond:
            while self._queue and self._error is None:
                self._cond.wait(0.5)
            abandoned = len(self._queue)
            self._closing = True
            self._cond.notify_all()
            err = self._error
            t = self._thread
        if t is not None:
            t.join(timeout=10.0)
        self._inner.close()
        if err is not None:
            _log.error(
                "state applier closed after a latched failure; %d "
                "queued batches abandoned (recover() replays them "
                "from the block files on reopen): %s", abandoned, err,
            )

"""The per-channel ledger: block store + state + history orchestration.

Analog of the reference's kvledger (core/ledger/kvledger/kv_ledger.go):
``commit_block`` mirrors kvLedger.commit (:612-731) — already-validated
block + its TRANSACTIONS_FILTER and prepared update batch go through:

  1. commit-hash chaining (:650) — sha256(prev_commit_hash ‖
     block-header hash ‖ tx filter), stored in the COMMIT_HASH
     metadata slot so peers can cross-check state equality;
  2. block+pvtdata store append (the source of truth);
  3. state-DB apply with the block height as savepoint;
  4. history-DB apply.

Crash recovery mirrors recoverDBs (:357): on open, state/history DBs
behind the block store are caught up by replaying stored blocks
through a replay callback (the committer's re-validation path), so a
crash between steps 2-4 self-heals.

Validation itself lives in fabric_tpu.peer.validator (the TPU
pipeline); the ledger takes its outputs, keeping the layering of the
reference (txmgr validates, kvledger orchestrates).
"""

from __future__ import annotations

import hashlib
import logging
import os

from fabric_tpu import protoutil
from fabric_tpu.ledger.blockstore import BlockStore
from fabric_tpu.ledger.history import HistoryDB
from fabric_tpu.ledger.pvtdata import PvtDataStore
from fabric_tpu.ledger.statedb import SqliteVersionedDB, UpdateBatch, VersionedDB
from fabric_tpu.observe import txflow as _txflow
from fabric_tpu.protos import common_pb2

_log = logging.getLogger("fabric_tpu.ledger")


class KVLedger:
    def __init__(
        self,
        ledger_dir: str,
        state_db: VersionedDB | None = None,
        enable_history: bool = True,
        async_commit: bool = False,
        apply_queue_blocks: int = 4,
    ):
        """``async_commit``: state-DB apply trails the block append on
        the background applier (ledger/committer.py) — reads stay
        consistent through the engine's pending overlay, the bounded
        queue (``apply_queue_blocks``) backpressures at the block
        boundary.  The peer/bench layers turn this ON by default
        (nodeconfig ``async_commit``, ``FABTPU_BENCH_ASYNC_COMMIT``);
        the library default stays serial so direct KVLedger users get
        apply-on-return semantics unless they opt in."""
        os.makedirs(ledger_dir, exist_ok=True)
        self.dir = ledger_dir
        self.blocks = BlockStore(os.path.join(ledger_dir, "chains"))
        inner = state_db or SqliteVersionedDB(os.path.join(ledger_dir, "state.db"))
        inner.open()
        self.engine = None
        if async_commit:
            from fabric_tpu.ledger.committer import AsyncApplyEngine

            self.engine = AsyncApplyEngine(
                inner, blocks=self.blocks,
                queue_blocks=apply_queue_blocks,
            )
        self.state = self.engine if self.engine is not None else inner
        self._reconcile_on_open()
        self.history = (
            HistoryDB(os.path.join(ledger_dir, "history.db")) if enable_history else None
        )
        self.pvtdata = PvtDataStore(os.path.join(ledger_dir, "pvtdata.db"))
        self._commit_hash: bytes | None = self._load_last_commit_hash()
        # per-commit critical-path decomposition (ledger_append = block
        # store + pvtdata, state_apply = state/history/purge — under
        # the async engine the latter is enqueue + backpressure only)
        self.last_commit_timings: dict = {}
        self._commit_hists = None  # lazy registry histograms

    def _reconcile_on_open(self) -> None:
        """Height/savepoint reconciliation (recoverDBs preamble): the
        savepoint BEHIND the block height is the normal crash shape —
        recover() replays the gap from the chain files.  A savepoint
        AHEAD of the files (a crash-truncated block tail under a
        durable state DB) cannot be replayed from anywhere; flag it
        loudly — redelivery from ordering re-commits the missing
        blocks and the savepoint self-heals by overwrite."""
        try:
            sp = self.state.savepoint()
        except Exception as e:
            _log.debug("savepoint unreadable at open (fresh or "
                       "still-initializing state DB): %s", e)
            return
        height = self.blocks.height
        if sp is not None and sp[0] + 1 > height:
            _log.warning(
                "state savepoint %s is ahead of block height %d; "
                "awaiting block redelivery to reconcile", sp, height,
            )

    # -- commit hash chain -------------------------------------------------

    def _load_last_commit_hash(self) -> bytes | None:
        h = self.blocks.height
        if h == 0:
            return None
        blk = self.blocks.get_block(h - 1)
        if blk is None:
            # snapshot-bootstrapped store with no post-snapshot blocks
            # yet: the chain anchor persists in the bootstrap record
            boot = self.blocks.bootstrap_info()
            return (boot[2] or None) if boot else None
        idx = common_pb2.BlockMetadataIndex.COMMIT_HASH
        if len(blk.metadata.metadata) > idx and blk.metadata.metadata[idx]:
            return blk.metadata.metadata[idx]
        return None

    def _next_commit_hash(self, block: common_pb2.Block, tx_filter: bytes) -> bytes:
        return hashlib.sha256(
            (self._commit_hash or b"")
            + protoutil.block_header_hash(block.header)
            + bytes(tx_filter)
        ).digest()

    # -- commit (kv_ledger.go:612) ----------------------------------------

    def commit_block(
        self,
        block: common_pb2.Block,
        tx_filter: bytes,
        batch: UpdateBatch,
        history_writes: list | None = None,
        pvt_data: dict | None = None,
        txids: list | None = None,
        hd_bytes: bytes | None = None,
    ) -> None:
        import time as _time

        num = block.header.number
        if num != self.blocks.height:
            raise ValueError(f"commit out of order: {num} vs height {self.blocks.height}")
        protoutil.set_tx_filter(block, tx_filter)
        commit_hash = self._next_commit_hash(block, tx_filter)
        idx = common_pb2.BlockMetadataIndex.COMMIT_HASH
        while len(block.metadata.metadata) <= idx:
            block.metadata.metadata.append(b"")
        block.metadata.metadata[idx] = commit_hash

        t0 = _time.perf_counter()
        self.blocks.add_block(block, txids=txids, hd_bytes=hd_bytes)
        if pvt_data:
            self.pvtdata.commit_block(num, pvt_data)
        t1 = _time.perf_counter()
        if self.engine is not None:
            # decoupled committer: the block is committed (appended);
            # state apply trails on the applier thread, which also
            # enforces the durability fence (ensure_synced) and runs
            # the history commit post-apply.  Cost here is enqueue +
            # any backpressure wait.
            post_apply = None
            if self.history is not None and history_writes:
                hist = self.history

                def post_apply(hist=hist, num=num, hw=history_writes):
                    hist.commit_block(num, hw)

            self.engine.submit(num, batch, (num, 0), post_apply=post_apply)
        else:
            if getattr(self.state, "durable", True):
                # a DURABLE state savepoint must never get ahead of the
                # block files (recover() replays forward from the
                # savepoint; a savepoint past a crash-truncated store
                # would skip replay and fork the peer) — close the
                # group window before the state commit.  Non-durable
                # backends (mem) recover by full replay, so they keep
                # the amortized-fsync fast path.
                self.blocks.sync()
                _txflow.block_durable(num)
            self.state.apply_updates(batch, (num, 0))
            if self.history is not None and history_writes:
                self.history.commit_block(num, history_writes)
            # serial path: writes are readable the moment apply (+
            # history) returns on the committer's own thread
            _txflow.block_applied(num)
        self._purge_expired_pvt(num)
        t2 = _time.perf_counter()
        self._commit_hash = commit_hash
        self.last_commit_timings = {
            "ledger_append": t1 - t0,
            "state_apply": t2 - t1,
        }
        hists = self._commit_hists
        if hists is None:
            from fabric_tpu.ops_metrics import global_registry

            reg = global_registry()
            hists = self._commit_hists = (
                reg.histogram("ledger_append_seconds",
                              "block-store append on the commit path"),
                reg.histogram("ledger_state_apply_seconds",
                              "state apply (or enqueue) on the commit path"),
            )
        hists[0].observe(t1 - t0)
        hists[1].observe(t2 - t1)

    def _purge_expired_pvt(self, num: int) -> None:
        """BTL expiry at the block boundary (pvtstatepurgemgmt analog):
        expired collections leave the pvtdata store AND the private
        state — both the cleartext namespace and the key-hash
        namespace (the hashes on the public rwset stay in the block
        history, but live state must not outlive block_to_live)."""
        import hashlib

        from fabric_tpu.ledger.pvtdata import decode_kv
        from fabric_tpu.ledger.statedb import UpdateBatch

        purged = self.pvtdata.purge_expired(num)
        if not purged:
            return
        batch = UpdateBatch()
        for blk_n, txnum, ns, coll, rwset in purged:
            try:
                kv = decode_kv(rwset)
            except Exception as e:
                _log.warning(
                    "pvt purge: undecodable rwset for %s/%s at block "
                    "%d tx %d: %s", ns, coll, blk_n, txnum, e,
                )
                continue
            hns = f"{ns}${coll}"
            for key in kv:
                # only purge if the LIVE state still carries this (or an
                # older) write: a later re-write has its own, later BTL
                # horizon and must survive (per-key expiry semantics of
                # pvtstatepurgemgmt)
                vv = self.state.get_state(hns, key)
                if vv is None or vv.version[0] > blk_n:
                    continue
                batch.delete(hns, key, (num, 0))
                kh = hashlib.sha256(
                    key.encode() if isinstance(key, str) else key
                ).hexdigest()
                batch.delete(f"{hns}#hashed", kh, (num, 0))
        if batch.updates:
            # re-assert the block's savepoint (passing None would reset
            # it on the mem backend and force a full recovery replay)
            self.state.apply_updates(batch, (num, 0))

    # -- recovery (kv_ledger.go:357 recoverDBs) ---------------------------

    def recover(self, replayer) -> int:
        """replayer(block) -> (tx_filter, UpdateBatch, history_writes);
        re-derives state for blocks the state DB is missing.  Returns
        the number of replayed blocks."""
        height = self.blocks.height
        sp = self.state.savepoint()
        start = (sp[0] + 1) if sp else 0
        replayed = 0
        for num in range(start, height):
            block = self.blocks.get_block(num)
            tx_filter, batch, history_writes = replayer(block)
            self.state.apply_updates(batch, (num, 0))
            if self.history is not None and history_writes:
                hsp = self.history.savepoint()
                if hsp is None or hsp < num:
                    self.history.commit_block(num, history_writes)
            replayed += 1
        # replay applies ride the normal queue under the async engine;
        # recovery is a barrier — callers read state right after
        self.drain_state()
        return replayed

    def drain_state(self) -> None:
        """Barrier on the async apply queue (no-op for the serial
        engine): returns once every enqueued batch has applied."""
        if self.engine is not None:
            self.engine.drain()

    def state_digest(self) -> str:
        """Content hash of the committed state (ledger/snapshot.py
        ``state_digest``), behind the async-apply drain barrier — the
        catch-up differential's equality oracle: snapshot-then-replay
        vs replay-from-genesis compare equal iff their committed
        records are byte-identical."""
        from fabric_tpu.ledger.snapshot import state_digest

        self.drain_state()
        return state_digest(self.state)

    @property
    def height(self) -> int:
        return self.blocks.height

    @property
    def commit_hash(self) -> bytes | None:
        return self._commit_hash

    def bootstrap_commit_hash(self, h: bytes | None) -> None:
        """Seed the commit-hash chain when joining from a snapshot
        (the chain continues from the snapshot's last commit hash)."""
        self._commit_hash = h

    def close(self):
        try:
            # state first: the async engine drains here, and its
            # applier fences against self.blocks / commits history —
            # both must still be open
            self.state.close()
        finally:
            self.blocks.close()
            if self.history is not None:
                self.history.close()
            self.pvtdata.close()

"""Analysis engine: findings, rule registry, noqa + baseline plumbing.

Rules subclass :class:`Rule` and register with :func:`register`.  A
rule sees either one module at a time (``check_module``) or the whole
analyzed set at once (``check_project`` — cross-module rules like
lock-order build a project graph first).  The engine owns everything
else: file discovery, parsing, ``# fabtpu: noqa(...)`` suppression,
and the baseline multiset for grandfathered findings.
"""

from __future__ import annotations

import ast
import json
import os
import re
import time
import tokenize
from collections import Counter
from dataclasses import asdict, dataclass, field

SEVERITIES = ("error", "warning")

# matches `# fabtpu: noqa` (suppress every rule on the line) or
# `# fabtpu: noqa(FT003)` / `# fabtpu: noqa(FT001, lock-discipline)`
_NOQA_RE = re.compile(
    r"#\s*fabtpu:\s*noqa(?:\s*\(\s*([A-Za-z0-9_,\-\s]*?)\s*\))?",
)


@dataclass(frozen=True)
class Finding:
    """One diagnostic: ``path:line:col RULE(name) message``."""

    rule: str      # stable id, e.g. "FT003"
    name: str      # human slug, e.g. "host-sync-in-hot-path"
    path: str      # repo-relative posix path
    line: int
    col: int
    severity: str  # "error" | "warning"
    message: str

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule}({self.name}) [{self.severity}] {self.message}"
        )

    def baseline_key(self) -> tuple:
        # line numbers drift with unrelated edits; a baseline entry
        # pins (rule, path, message) instead
        return (self.rule, self.path, self.message)

    def to_json(self) -> dict:
        return asdict(self)


class ModuleCtx:
    """One parsed module: tree + source + noqa map."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.noqa = self._parse_noqa(source)

    @staticmethod
    def _parse_noqa(source: str) -> dict[int, set[str] | None]:
        """line → suppressed rule ids/names (None = every rule).

        Comments are found with the tokenizer, not a per-line regex,
        so a ``# fabtpu: noqa`` inside a string literal is inert."""
        out: dict[int, set[str] | None] = {}
        import io

        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _NOQA_RE.search(tok.string)
                if not m:
                    continue
                line = tok.start[0]
                if m.group(1) is None:
                    out[line] = None
                elif out.get(line, set()) is not None:
                    got = out.setdefault(line, set())
                    got.update(
                        s.strip() for s in m.group(1).split(",") if s.strip()
                    )
        except tokenize.TokenError:
            pass
        return out

    def suppressed(self, rule: "Rule", line: int) -> bool:
        if line not in self.noqa:
            return False
        rules = self.noqa[line]
        return rules is None or rule.id in rules or rule.name in rules


def is_test_path(relpath: str) -> bool:
    """Test code is exempt from every rule (``exempt_tests``):
    fixtures and differentials drive bad shapes on purpose."""
    rel = relpath.replace("\\", "/")
    base = rel.rsplit("/", 1)[-1]
    return ("tests/" in rel or rel.startswith("tests")
            or base.startswith("test_") or base == "conftest.py")


class Rule:
    """Base class: subclass, set ``id``/``name``/``severity``, and
    implement ``check_module`` (per-file) and/or ``check_project``
    (cross-file, runs once with every analyzed module).  The engine
    skips test files for every rule with ``exempt_tests`` (the
    default — the whole battery polices production code; tests pin
    bad shapes on purpose)."""

    id: str = ""
    name: str = ""
    severity: str = "error"
    description: str = ""
    exempt_tests: bool = True

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        return []

    def check_project(self, modules: list[ModuleCtx]) -> list[Finding]:
        return []

    def finding(self, ctx_or_path, line: int, col: int, message: str) -> Finding:
        path = (
            ctx_or_path.relpath
            if isinstance(ctx_or_path, ModuleCtx)
            else ctx_or_path
        )
        return Finding(
            rule=self.id, name=self.name, path=path, line=line, col=col,
            severity=self.severity, message=message,
        )


_REGISTRY: dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate + register a rule by id."""
    rule = cls()
    if not rule.id or not rule.name:
        raise ValueError(f"rule {cls.__name__} must set id and name")
    if rule.severity not in SEVERITIES:
        raise ValueError(f"rule {rule.id}: bad severity {rule.severity!r}")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id}")
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> list[Rule]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


# -- discovery + run --------------------------------------------------------

_SKIP_SUFFIXES = ("_pb2.py",)  # generated protobuf modules


def _iter_py_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if d not in ("__pycache__", ".git", ".jax_cache")
            )
            for f in sorted(files):
                if f.endswith(".py") and not f.endswith(_SKIP_SUFFIXES):
                    yield os.path.join(root, f)


def _relpath(path: str, root: str | None) -> str:
    if root:
        try:
            return os.path.relpath(path, root).replace(os.sep, "/")
        except ValueError:
            pass
    return path.replace(os.sep, "/")


def load_modules(paths: list[str], root: str | None = None) -> tuple[list[ModuleCtx], list[Finding]]:
    """Parse every .py under ``paths``.  Unparseable files become
    FT000 findings (a syntax error is never 'clean')."""
    modules: list[ModuleCtx] = []
    errors: list[Finding] = []
    for path in _iter_py_files(paths):
        rel = _relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            modules.append(ModuleCtx(path, rel, source))
        except (SyntaxError, ValueError, UnicodeDecodeError, OSError) as e:
            # ValueError: ast.parse on source with NUL bytes
            errors.append(Finding(
                rule="FT000", name="parse-error", path=rel,
                line=getattr(e, "lineno", 0) or 0, col=0,
                severity="error", message=f"cannot analyze: {e}",
            ))
    return modules, errors


def load_baseline(path: str | None) -> Counter:
    """Baseline file → multiset of (rule, path, message) keys.  Each
    entry absorbs exactly ``count`` (default 1) occurrences — fixing
    one of two grandfathered findings shrinks the budget, it does not
    hide the survivor."""
    if not path or not os.path.exists(path):
        return Counter()
    with open(path, encoding="utf-8") as f:
        raw = json.load(f)
    counts: Counter = Counter()
    for entry in raw.get("findings", []):
        key = (entry["rule"], entry["path"], entry["message"])
        counts[key] += int(entry.get("count", 1))
    return counts


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


@dataclass
class AnalysisResult:
    findings: list[Finding]          # live (post-noqa, post-baseline)
    baselined: list[Finding]
    suppressed: int                  # count silenced by noqa
    stale_baseline: list[tuple]      # baseline keys nothing matched
    timings: dict[str, float] = field(default_factory=dict)  # rule → s

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "error"]


def analyze_paths(
    paths: list[str],
    root: str | None = None,
    rules: list[Rule] | None = None,
    baseline: Counter | None = None,
) -> AnalysisResult:
    """Run ``rules`` (default: the full registry) over ``paths``."""
    if rules is None:
        rules = all_rules()
    modules, parse_errors = load_modules(paths, root=root)
    by_rel = {m.relpath: m for m in modules}

    raw: list[Finding] = list(parse_errors)
    timings: dict[str, float] = {}
    for rule in rules:
        t0 = time.perf_counter()
        for m in modules:
            if rule.exempt_tests and is_test_path(m.relpath):
                continue
            raw.extend(rule.check_module(m))
        project = rule.check_project(modules)
        if rule.exempt_tests:
            project = [f for f in project if not is_test_path(f.path)]
        raw.extend(project)
        timings[rule.id] = timings.get(rule.id, 0.0) + (
            time.perf_counter() - t0
        )

    # noqa pass — a finding carries the rule that made it, so look the
    # rule back up by id (parse errors are never suppressible)
    live: list[Finding] = []
    suppressed = 0
    for f in raw:
        rule = _REGISTRY.get(f.rule)
        m = by_rel.get(f.path)
        if rule is not None and m is not None and m.suppressed(rule, f.line):
            suppressed += 1
        else:
            live.append(f)

    # baseline pass
    budget = Counter(baseline or ())
    kept: list[Finding] = []
    baselined: list[Finding] = []
    for f in sorted(live, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = f.baseline_key()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            baselined.append(f)
        else:
            kept.append(f)
    stale = [k for k, n in budget.items() if n > 0]
    return AnalysisResult(
        findings=kept, baselined=baselined,
        suppressed=suppressed, stale_baseline=stale,
        timings=timings,
    )


# -- shared AST helpers (used by several rules) -----------------------------


def dotted_name(node: ast.AST) -> str | None:
    """``ast.Attribute``/``ast.Name`` → dotted string ("jax.jit"),
    else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    return dotted_name(node.func)


def walk_functions(tree: ast.AST):
    """Yield every FunctionDef/AsyncFunctionDef in the tree."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node

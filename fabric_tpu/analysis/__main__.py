"""CLI: ``python -m fabric_tpu.analysis [paths...]``.

Exit status 0 = clean (baselined findings allowed), 1 = live
findings, 2 = usage error.  ``--json`` emits machine-readable output
for CI; the default renderer prints ``path:line:col: RULE(name)
[severity] message`` lines plus a summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from fabric_tpu.analysis import (
    all_rules,
    analyze_paths,
    load_baseline,
)
from fabric_tpu.analysis.core import default_baseline_path


def _repo_root() -> str:
    # fabric_tpu/analysis/__main__.py → repo root two levels up from
    # the package directory
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fabric_tpu.analysis",
        description="JAX/concurrency static analysis for fabric_tpu",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: fabric_tpu/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: the checked-in "
                         "fabric_tpu/analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show every finding)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule battery and exit")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule id/name (repeatable)")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.name:<24} [{r.severity}] {r.description}")
        return 0

    if args.rule:
        want = set(args.rule)
        rules = [r for r in rules if r.id in want or r.name in want]
        if not rules:
            print(f"no rule matches {sorted(want)}", file=sys.stderr)
            return 2

    root = _repo_root()
    paths = args.paths or [os.path.join(root, "fabric_tpu")]
    baseline = (
        None if args.no_baseline
        else load_baseline(args.baseline or default_baseline_path())
    )
    result = analyze_paths(paths, root=root, rules=rules, baseline=baseline)

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in result.findings],
            "baselined": [f.to_json() for f in result.baselined],
            "suppressed": result.suppressed,
            "stale_baseline": [list(k) for k in result.stale_baseline],
        }, indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(f.render())
        bits = [f"{len(result.findings)} finding(s)"]
        if result.baselined:
            bits.append(f"{len(result.baselined)} baselined")
        if result.suppressed:
            bits.append(f"{result.suppressed} noqa-suppressed")
        if result.stale_baseline:
            bits.append(
                f"{len(result.stale_baseline)} STALE baseline entr"
                f"{'y' if len(result.stale_baseline) == 1 else 'ies'} "
                f"(fixed findings — prune them)"
            )
        print("fabric_tpu.analysis: " + ", ".join(bits))
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())

"""CLI: ``python -m fabric_tpu.analysis [paths...]``.

Exit status 0 = clean (baselined findings allowed), 1 = live
findings OR stale baseline entries, 2 = usage error.  ``--json``
emits machine-readable output for CI (including per-rule wall-time
under ``timings``); ``--sarif`` emits a SARIF 2.1.0 log for code
scanners; ``--changed [REF]`` analyzes only files that differ from a
git ref (project-wide rules still see the full tree — a change
anywhere can create a cross-module finding elsewhere).  The default
renderer prints ``path:line:col: RULE(name) [severity] message``
lines plus a summary.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from collections import Counter

from fabric_tpu.analysis import (
    all_rules,
    analyze_paths,
    load_baseline,
)
from fabric_tpu.analysis.core import (
    AnalysisResult,
    Rule,
    default_baseline_path,
)

_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _repo_root() -> str:
    # fabric_tpu/analysis/__main__.py → repo root two levels up from
    # the package directory
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _changed_paths(root: str, ref: str) -> list[str] | None:
    """Analyzable .py files differing from ``ref`` (``git diff
    --name-only`` plus untracked), absolute.  None = git failed."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        if diff.returncode != 0:
            sys.stderr.write(diff.stderr)
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30,
        )
        names = diff.stdout.splitlines() + (
            untracked.stdout.splitlines()
            if untracked.returncode == 0 else []
        )
    except (OSError, subprocess.SubprocessError) as e:
        sys.stderr.write(f"git diff failed: {e}\n")
        return None
    out = []
    for name in names:
        if not name.endswith(".py"):
            continue
        path = os.path.join(root, name)
        if os.path.exists(path):  # deleted files have nothing to parse
            out.append(path)
    return sorted(set(out))


def _is_project_rule(rule: Rule) -> bool:
    return type(rule).check_project is not Rule.check_project


def _merge(a: AnalysisResult, b: AnalysisResult) -> AnalysisResult:
    order = lambda f: (f.path, f.line, f.col, f.rule)
    timings = Counter(a.timings)
    timings.update(b.timings)
    return AnalysisResult(
        findings=sorted(a.findings + b.findings, key=order),
        baselined=sorted(a.baselined + b.baselined, key=order),
        suppressed=a.suppressed + b.suppressed,
        stale_baseline=[],  # partial runs cannot judge staleness
        timings=dict(timings),
    )


def _to_sarif(result: AnalysisResult, rules: list[Rule]) -> dict:
    ids = {f.rule for f in result.findings}
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "fabric_tpu.analysis",
                "informationUri":
                    "https://example.invalid/fabric-tpu/analysis",
                "rules": [
                    {
                        "id": r.id,
                        "name": r.name,
                        "shortDescription": {"text": r.description},
                    }
                    for r in rules if r.id in ids or not ids
                ],
            }},
            "results": [
                {
                    "ruleId": f.rule,
                    "level": f.severity,
                    "message": {"text": f.message},
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {"uri": f.path},
                            "region": {
                                "startLine": max(f.line, 1),
                                "startColumn": f.col + 1,
                            },
                        },
                    }],
                }
                for f in result.findings
            ],
        }],
    }


def _write_baseline(path: str, result: AnalysisResult) -> None:
    """Rewrite the baseline from the live run: every finding the run
    produced (kept + previously-baselined) becomes budget."""
    counts: Counter = Counter(
        f.baseline_key() for f in result.findings + result.baselined
    )
    entries = [
        {"rule": rule, "path": p, "message": msg, "count": n}
        for (rule, p, msg), n in sorted(counts.items())
    ]
    payload = {
        "_comment": (
            "Grandfathered findings: each entry absorbs `count` "
            "occurrences matching (rule, path, message). Keep this "
            "empty — fix findings instead of baselining them; the "
            "mechanism exists for emergencies and for staging large "
            "rule rollouts."
        ),
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m fabric_tpu.analysis",
        description="JAX/concurrency static analysis for fabric_tpu",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: fabric_tpu/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON (with per-rule timings)")
    ap.add_argument("--sarif", action="store_true",
                    help="emit a SARIF 2.1.0 log")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: the checked-in "
                         "fabric_tpu/analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (show every finding)")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="rewrite the baseline file from this run's "
                         "findings and exit 0")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule battery and exit")
    ap.add_argument("--rule", action="append", default=None,
                    help="run only this rule id/name (repeatable)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="analyze only .py files differing from REF "
                         "(default HEAD); project-wide rules still "
                         "scan the full tree")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.name:<24} [{r.severity}] {r.description}")
        return 0

    if args.rule:
        want = set(args.rule)
        rules = [r for r in rules if r.id in want or r.name in want]
        if not rules:
            print(f"no rule matches {sorted(want)}", file=sys.stderr)
            return 2
    if args.sarif and args.as_json:
        print("--sarif and --json are mutually exclusive",
              file=sys.stderr)
        return 2

    root = _repo_root()
    paths = args.paths or [os.path.join(root, "fabric_tpu")]
    baseline_path = args.baseline or default_baseline_path()
    baseline = None if args.no_baseline else load_baseline(baseline_path)

    if args.changed is not None:
        changed = _changed_paths(root, args.changed)
        if changed is None:
            return 2
        if not changed:
            result = AnalysisResult(
                findings=[], baselined=[], suppressed=0,
                stale_baseline=[],
            )
        else:
            module_rules = [r for r in rules if not _is_project_rule(r)]
            project_rules = [r for r in rules if _is_project_rule(r)]
            result = analyze_paths(
                changed, root=root, rules=module_rules,
                baseline=baseline,
            )
            if project_rules:
                # a changed module in a project rule's dependency set
                # can surface findings in UNCHANGED modules — run the
                # cross-module rules over the full requested tree
                result = _merge(result, analyze_paths(
                    paths, root=root, rules=project_rules,
                    baseline=baseline,
                ))
    else:
        result = analyze_paths(
            paths, root=root, rules=rules, baseline=baseline,
        )

    if args.fix_baseline:
        _write_baseline(baseline_path, result)
        n = len(result.findings) + len(result.baselined)
        print(f"fabric_tpu.analysis: baseline rewritten with {n} "
              f"entr{'y' if n == 1 else 'ies'} → {baseline_path}")
        return 0

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in result.findings],
            "baselined": [f.to_json() for f in result.baselined],
            "suppressed": result.suppressed,
            "stale_baseline": [list(k) for k in result.stale_baseline],
            "timings": {k: round(v, 6)
                        for k, v in sorted(result.timings.items())},
        }, indent=2, sort_keys=True))
    elif args.sarif:
        print(json.dumps(_to_sarif(result, rules), indent=2))
    else:
        for f in result.findings:
            print(f.render())
        bits = [f"{len(result.findings)} finding(s)"]
        if result.baselined:
            bits.append(f"{len(result.baselined)} baselined")
        if result.suppressed:
            bits.append(f"{result.suppressed} noqa-suppressed")
        print("fabric_tpu.analysis: " + ", ".join(bits))
        if result.stale_baseline:
            print(
                "fabric_tpu.analysis: STALE baseline — "
                f"{len(result.stale_baseline)} entr"
                f"{'y' if len(result.stale_baseline) == 1 else 'ies'} "
                "matched nothing (the findings are fixed); run with "
                "--fix-baseline to prune:",
                file=sys.stderr,
            )
            for key in sorted(result.stale_baseline):
                rule, path, msg = key
                print(f"  {rule} {path}: {msg}", file=sys.stderr)
    if result.findings:
        return 1
    if result.stale_baseline:
        return 1  # a stale baseline is a lint failure: prune it
    return 0


if __name__ == "__main__":
    sys.exit(main())

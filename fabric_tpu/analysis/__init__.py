"""AST-based static analysis for the fabric_tpu tree.

The jitted kernels (ops/), the lock-heavy host code (peer/, ordering/,
gossip) and the config/crypto layers each have defect classes that
only surface as a flaky test or a bench regression: impure jitted
functions, retrace hazards, host-device syncs on the commit path,
lock-order inversions, swallowed exceptions, and env-override coercion
of non-scalar config fields.  This package is the `go vet` + race
detector analog for that tree: a rule registry over the stdlib `ast`
module, per-rule severity, inline ``# fabtpu: noqa(RULE)``
suppressions, and a checked-in baseline for grandfathered findings.

Run it:

    python -m fabric_tpu.analysis fabric_tpu/
    python scripts/lint.py            # same thing
    python -m fabric_tpu.analysis --json fabric_tpu/ordering/

``tests/test_static_analysis.py`` runs the analyzer over the whole
package in-process and fails on any non-baselined finding, so tier-1
enforces a clean tree forever.  See README.md for the rule-writing and
baseline workflow.
"""

from fabric_tpu.analysis.core import (  # noqa: F401
    Finding,
    ModuleCtx,
    Rule,
    all_rules,
    analyze_paths,
    load_baseline,
    register,
)

# importing the rules package registers the built-in battery
from fabric_tpu.analysis import rules as _rules  # noqa: F401,E402

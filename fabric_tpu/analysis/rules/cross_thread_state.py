"""FT017 cross-thread-state: shared self-attrs reached from two
thread roles with no common lock.

The PR-13 shape, statically: the gateway's submit-queue deque was
appended by the ingest thread and drained by the flusher with the
class's own lock held on only ONE of the two paths — a race that
corrupts under load and never under test.  This rule infers which
methods of a class run on which thread and flags attributes provably
reachable from two roles without a common lock.

**Thread roles**, from spawn sites (:func:`thread_spawn_roles` —
anything unprovable stays silent):

* ``threading.Thread(target=self.m)`` — import-aware; ``self.m`` must
  be a method of the class (the repo has ~11 in-tree spawn sites of
  this shape);
* ``self.<ex>.submit(self.m, ...)`` where ``<ex>`` is a ctor-proven
  ``ThreadPoolExecutor`` / ``ProcessPoolExecutor`` attr;
* ``asyncio.create_task(self.m(...))`` / ``asyncio.ensure_future`` /
  ``asyncio.run_coroutine_threadsafe`` — import-aware; the coroutine
  interleaves with every other task on the loop (awaits are the
  preemption points), so against a real thread its state shares
  exactly like a thread's.  Between two tasks on the SAME loop the
  scheduling is cooperative: every task role (and every ``async def``
  caller entry) implicitly holds the synthetic ``<event-loop>``
  token, so loop-internal sharing — the sync-surface-plus-loop-thread
  idiom — never pairs task-vs-task, only task-vs-thread;
* ``<loop>.run_in_executor(executor, self.m, ...)`` — the method runs
  on a pool thread regardless of which loop object carries the call;
* the **caller role**: every public method that is not itself a spawn
  entry — the application thread driving the object.  ``__init__`` is
  excluded outright: it runs before any thread exists.

Each role's reachable accesses close over the intra-class call graph
(``self.m()`` edges) with the held-lock set propagated
interprocedurally — a ``_flush_locked`` helper invoked under ``with
self._cond:`` counts as locked, so the repo's ``*_locked`` idiom is
clean by construction.  A call edge INTO a spawn-entry method does
not extend the caller's body: ``run_coroutine_threadsafe(
self._asubmit(...))`` ships the coroutine to the loop thread, so
``_asubmit``'s accesses belong to its task role, not to the caller.

**The race predicate**, strictly under-approximating:

* the attr is reached from ≥ 2 distinct roles, and
* at least one of those accesses is a write (attr store, aug-assign,
  subscript store, or a container mutator like ``.append``), and
* some pair of accesses from different roles — one of them a write —
  provably holds NO common lock, and
* at least one access of the attr somewhere holds SOME lock: a class
  that never locks the attr at all (stop-flag booleans, config set
  once before start) expresses a different discipline the rule cannot
  prove wrong, so it stays silent.

Reassigned or unknown-provenance spawn targets never create roles;
one finding per (class, attr), anchored at the unlocked access
(writes preferred).  Suppress an intended benign race (monotonic
flag handshakes) with ``# fabtpu: noqa(FT017)`` on that line.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import Finding, ModuleCtx, Rule, register
from fabric_tpu.analysis.provenance import module_index
from fabric_tpu.analysis.rules._threads import (
    scan_class,
    thread_spawn_roles,
)


@register
class CrossThreadStateRule(Rule):
    id = "FT017"
    name = "cross-thread-state"
    severity = "error"
    description = (
        "flags self-attributes reached from two inferred thread roles "
        "(Thread targets, executor submits, asyncio task spawns, "
        "run_in_executor dispatches, public-method callers) "
        "where some cross-role access pair provably holds no common "
        "lock while the class locks the same attr elsewhere — the "
        "unlocked-deque class of race"
    )

    def check_project(self, modules: list[ModuleCtx]) -> list[Finding]:
        out: list[Finding] = []
        for ctx in modules:
            idx = module_index(ctx)
            for cls in idx.classes:
                out.extend(self._check_class(ctx, idx, cls))
        out.sort(key=lambda f: (f.path, f.line, f.col))
        return out

    def _check_class(self, ctx: ModuleCtx, idx, cls: ast.ClassDef):
        methods = idx.class_methods(cls)
        spawned = thread_spawn_roles(cls, methods, idx.imports)
        if not spawned:
            return []  # single-threaded class: nothing to race
        lock_names, scans = scan_class(cls, methods, idx.imports)

        roles: dict[str, list[str]] = {
            role: [m] for m, role in spawned.items()
        }
        callers = [
            m for m in methods
            if not m.startswith("_") and m not in spawned
        ]
        if callers:
            roles["caller"] = callers

        # closure: accesses reachable from an entry method, with the
        # entry-held set layered onto each access's lexical held set
        memo: dict[tuple, list] = {}

        def collect(mname: str, entry_held: frozenset, stack: frozenset):
            key = (mname, entry_held)
            if key in memo:
                return memo[key]
            if mname in stack or mname not in scans:
                return []
            accesses, calls = scans[mname]
            got = [
                a if not entry_held
                else type(a)(a.attr, a.kind, a.line, a.col,
                             a.held | entry_held)
                for a in accesses
            ]
            for c in calls:
                if c.callee in spawned:
                    # a spawn entry's body runs on ITS role's
                    # schedule, not the caller's — the syntactic edge
                    # (e.g. run_coroutine_threadsafe(self.m())) does
                    # not extend the calling body
                    continue
                got.extend(collect(
                    c.callee, entry_held | c.held, stack | {mname},
                ))
            memo[key] = got
            return got

        # cooperative scheduling on one loop: task roles (and async
        # caller entries, which await on the same loop) mutually
        # exclude between awaits — modeled as an implicit common token
        loop_seed = frozenset({"<event-loop>"})

        per_attr: dict[str, dict[str, list]] = {}
        empty = frozenset()
        for role, entries in roles.items():
            for entry in entries:
                seed = empty
                if (role.startswith("task(")
                        or isinstance(methods.get(entry),
                                      ast.AsyncFunctionDef)):
                    seed = loop_seed
                for a in collect(entry, seed, frozenset()):
                    per_attr.setdefault(a.attr, {}) \
                            .setdefault(role, []).append(a)

        findings = []
        for attr in sorted(per_attr):
            if attr in methods:
                continue  # a bound-method reference, not state
            by_role = per_attr[attr]
            if len(by_role) < 2:
                continue
            every = [a for accs in by_role.values() for a in accs]
            if not any(a.kind == "write" for a in every):
                continue
            if not any(a.held - loop_seed for a in every):
                continue  # never locked anywhere: different discipline
                # (the synthetic loop token is not a chosen lock)
            pair = self._racing_pair(by_role)
            if pair is None:
                continue
            (r1, a1), (r2, a2) = pair
            anchor = a1 if (a1.kind == "write" and not a1.held) else a2
            other = a2 if anchor is a1 else a1
            o_role = r2 if anchor is a1 else r1
            a_role = r1 if anchor is a1 else r2
            held_txt = (
                f"under {', '.join(sorted(other.held))}"
                if other.held else "also unlocked"
            )
            findings.append(self.finding(
                ctx, anchor.line, anchor.col,
                f"self.{attr} in class {cls.name} is shared across "
                f"thread roles with no common lock: {anchor.kind} "
                f"here on role {a_role} holds "
                f"{'no lock' if not anchor.held else ', '.join(sorted(anchor.held))}"
                f" while role {o_role} {other.kind}s it at line "
                f"{other.line} {held_txt} — interleavings corrupt "
                f"state under load and never under test; hold the "
                f"class lock on every cross-thread path (the "
                f"*_locked helper idiom), or carry a "
                f"# fabtpu: noqa(FT017) saying why this handshake "
                f"is safe",
            ))
        return findings

    @staticmethod
    def _racing_pair(by_role: dict[str, list]):
        """First cross-role access pair (one a write) with disjoint
        held-sets, preferring a pair whose anchor is an unlocked
        write; deterministic order."""
        role_names = sorted(by_role)
        best = None
        for i, r1 in enumerate(role_names):
            for r2 in role_names[i + 1:]:
                for a1 in by_role[r1]:
                    for a2 in by_role[r2]:
                        if a1.kind != "write" and a2.kind != "write":
                            continue
                        if a1.held & a2.held:
                            continue
                        pair = ((r1, a1), (r2, a2))
                        unlocked_write = (
                            (a1.kind == "write" and not a1.held)
                            or (a2.kind == "write" and not a2.held)
                        )
                        if unlocked_write:
                            return pair
                        if best is None:
                            best = pair
        return best

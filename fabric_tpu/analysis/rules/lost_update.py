"""FT018 lost-update: unlocked read-modify-write of an attr the
class guards elsewhere.

The PR-12 lost-actuation class, statically: the autopilot applied a
knob step computed from a stale read of shared state — two writers
interleave, the second's write is computed from a value the first
already replaced, and one update silently vanishes.  No crash, no
torn structure, just a state transition that never happened.

This rule flags a read-modify-write of a shared ``self.`` attribute
performed while holding NO lock, in a class that demonstrably guards
the SAME attribute under a lock somewhere else — the class has
already declared the attr to be shared mutable state; the unlocked
RMW is the path that forgot.

**RMW shapes** (all three anchored at the write):

* ``self.a += step`` — augmented assignment, the classic;
* ``x = self.a`` … ``self.a = f(x)`` — the value being stored
  references the attr directly, or through a SINGLE-ASSIGNMENT local
  bound from it (``SingleAssignScope`` — a reassigned local has
  unknown provenance and stays silent);
* check-then-act — ``if self.a is None: self.a = ...`` — a test that
  reads the attr guarding a store to it.

**Lock evidence**, via the shared scan (:mod:`._threads`): lexical
``with self._lock:`` tracking plus interprocedural entry-held sets —
a private method whose EVERY intra-class call site provably holds a
lock inherits it (the ``*_locked`` helper idiom); public methods
inherit nothing (an external caller holds nothing provable).
Holding ANY lock at the RMW silences — the rule proves only the
"forgot the lock entirely" path, not lock-mismatch (FT017's job).

Deliberate single-threaded-phase RMWs carry a
``# fabtpu: noqa(FT018)`` saying why no second writer can exist.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import Finding, ModuleCtx, Rule, register
from fabric_tpu.analysis.provenance import module_index
from fabric_tpu.analysis.rules._threads import (
    _with_lock_token,
    scan_class,
    self_attr,
)


def _refs_attr(expr: ast.AST, attr: str, scope) -> bool:
    """Does ``expr`` read ``self.<attr>`` — directly, or through a
    single-assignment local bound from it?"""
    for node in ast.walk(expr):
        if self_attr(node) == attr and isinstance(node.ctx, ast.Load):
            return True
        if isinstance(node, ast.Name):
            src = scope.value_of(node.id)
            if src is not None and self_attr(src) == attr:
                return True
    return False


def _entry_held(methods: dict, scans: dict) -> dict[str, frozenset]:
    """Interprocedural entry-held sets: a private method whose every
    intra-class call site holds lock L enters with L held; public
    methods (and uncalled private ones) enter with nothing.  Fixed
    point over the call graph — monotone-decreasing intersections,
    converges in a handful of rounds."""
    empty = frozenset()
    sites: dict[str, list] = {m: [] for m in methods}
    for caller, (_, calls) in scans.items():
        for c in calls:
            if c.callee in sites:
                sites[c.callee].append((caller, c.held))
    entry: dict[str, frozenset] = {}
    for m in methods:
        if m.startswith("_") and not m.startswith("__") and sites[m]:
            entry[m] = None  # unconstrained until first round
        else:
            entry[m] = empty
    for _ in range(len(methods) + 1):
        changed = False
        for m, callers in sites.items():
            if entry[m] == empty or not callers:
                continue
            acc = None  # TOP: no call site has constrained it yet
            for caller, held in callers:
                caller_entry = entry.get(caller, empty)
                if caller_entry is None:
                    continue  # caller itself unresolved: contributes TOP
                site = held | caller_entry
                acc = site if acc is None else (acc & site)
            if acc is not None and acc != entry[m]:
                entry[m] = acc
                changed = True
        if not changed:
            break
    # a private-only cycle can stay TOP forever: it over-claims locks,
    # which only SILENCES findings — the safe direction
    return {m: (h if h is not None else empty) for m, h in entry.items()}


@register
class LostUpdateRule(Rule):
    id = "FT018"
    name = "lost-update"
    severity = "error"
    description = (
        "flags unlocked read-modify-write of a self-attribute "
        "(augmented assign, read-then-store, check-then-act) in a "
        "class that guards the same attribute under a lock elsewhere "
        "— interleaved writers silently drop an update, the "
        "lost-actuation class of bug"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        idx = module_index(ctx)
        out: list[Finding] = []
        for cls in idx.classes:
            methods = idx.class_methods(cls)
            lock_names, scans = scan_class(cls, methods, idx.imports)
            if not lock_names:
                continue  # lock-free class: guards-elsewhere unprovable
            guarded = {
                a.attr
                for accs, _ in scans.values()
                for a in accs
                if a.held
            }
            if not guarded:
                continue
            entry = _entry_held(methods, scans)
            for mname, fn in methods.items():
                if mname == "__init__":
                    continue  # construction precedes sharing
                flagged: set[tuple] = set()
                self._scan_rmw(
                    ctx, cls, fn, idx.scope(fn), lock_names, guarded,
                    entry.get(mname, frozenset()), flagged, out,
                )
        out.sort(key=lambda f: (f.line, f.col))
        return out

    def _scan_rmw(self, ctx, cls, fn, scope, lock_names, guarded,
                  entry_held, flagged, out):
        def emit(attr: str, node: ast.AST, shape: str):
            key = (attr, node.lineno)
            if key in flagged:
                return
            flagged.add(key)
            out.append(self.finding(
                ctx, node.lineno, node.col_offset,
                f"unlocked read-modify-write ({shape}) of "
                f"self.{attr} in {cls.name}.{fn.name} — the class "
                f"guards self.{attr} under a lock elsewhere, so a "
                f"concurrent writer can interleave between this "
                f"read and write and one update silently vanishes; "
                f"hold the lock across the whole read-modify-write, "
                f"or carry a # fabtpu: noqa(FT018) saying why no "
                f"second writer can exist here",
            ))

        def visit(node: ast.AST, held: frozenset):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in node.items:
                    tok = _with_lock_token(item, lock_names)
                    if tok is not None:
                        inner.add(tok)
                inner_f = frozenset(inner)
                for stmt in node.body:
                    visit(stmt, inner_f)
                return
            if not held:
                if isinstance(node, ast.AugAssign):
                    attr = self_attr(node.target)
                    if attr in guarded:
                        emit(attr, node, "augmented assign")
                elif (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    attr = self_attr(node.targets[0])
                    if (attr in guarded
                            and _refs_attr(node.value, attr, scope)):
                        emit(attr, node, "read-then-store")
                elif isinstance(node, ast.If):
                    self._check_then_act(node, held, guarded, emit)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, frozenset(entry_held))

    @staticmethod
    def _check_then_act(node: ast.If, held, guarded, emit):
        tested = {
            self_attr(n) for n in ast.walk(node.test)
            if self_attr(n) in guarded
        }
        if not tested:
            return

        def find_stores(stmt: ast.AST):
            # a store under a With in the body re-checks under lock
            # (double-checked idiom) — don't cross it; nested defs run
            # on their own schedule
            if isinstance(stmt, (ast.With, ast.AsyncWith,
                                 ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            target = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = self_attr(stmt.targets[0])
            elif isinstance(stmt, ast.AugAssign):
                target = self_attr(stmt.target)
            if target in tested:
                emit(target, stmt, "check-then-act")
            for child in ast.iter_child_nodes(stmt):
                find_stores(child)

        for stmt in node.body:
            find_stores(stmt)

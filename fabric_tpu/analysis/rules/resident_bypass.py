"""FT015 resident-state-bypass: committed-store writes that skip the
residency cache's invalidation hook.

The device-resident MVCC cache (``fabric_tpu/state/residency.py``)
mirrors the committed version store in device memory.  The ONE
coherence rule is that every write to the committed store must reach
the cache — either as the commit-boundary delta scatter
(``ResidencyManager.apply_batch``) or, when the delta is unknown, as
an invalidation (``invalidate_keys`` / ``disable``).  A
``state.apply_updates(...)`` that bypasses the hook leaves a STALE
version resident: the next block's device compare judges reads
against a world that no longer exists — a silent MVCC verdict
corruption, the worst failure class this repo has (verdicts fork from
the host oracle with no error anywhere).

Mechanics (strictly under-approximating, per the FT003..FT014
contract — a finding is always real):

1. **A manager must be provably in hand.**  Two binding shapes count,
   both import-aware (the FT003 lesson — a same-named local helper
   never matches):

   * a LOCAL assigned exactly once from ``ResidencyManager(...)`` or
     ``resolve_residency(...)`` — bare from-imports of
     ``fabric_tpu.state`` / ``fabric_tpu.state.residency`` (aliases
     tracked) or dotted calls through a tracked module alias;
   * a SELF-ATTR assigned from one of those ctors anywhere in the
     same class (``self.resident = ResidencyManager(...)``).

   A scope with no visible manager binding never flags — the rule
   polices code that HAS the cache and forgets it, not code that has
   never heard of it.
2. **The writer**: any ``<recv>.apply_updates(...)`` call in that
   scope (the ``VersionedDB`` committed-store writer — the method
   name is specific enough that, combined with rule 1's manager
   requirement, a false pairing requires a same-scope manager AND an
   unrelated ``apply_updates`` — accepted residual risk: zero such
   shapes exist in the repo).
3. **The hook**: the finding is suppressed when the SAME scope also
   touches the manager's coherence family — ``apply_batch``,
   ``invalidate_keys`` or ``disable`` — on a bound manager (local or
   class self-attr).
4. **Test code is exempt** (``tests/``, ``test_*.py``,
   ``conftest.py``) — differentials drive stale-cache shapes on
   purpose.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    dotted_name,
    register,
    walk_functions,
)

_CTORS = {"ResidencyManager", "resolve_residency"}
_HOOKS = {"apply_batch", "invalidate_keys", "disable"}
_WRITER = "apply_updates"
_STATE_MODULES = ("fabric_tpu.state", "fabric_tpu.state.residency")


def _bindings(tree: ast.Module):
    """→ (bare ctor names, module aliases) from the module's imports.
    A local def/class named like a ctor SHADOWS the bare import —
    dropped from the bare set."""
    bare: set[str] = set()
    aliases: set[str] = set()
    local_defs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if mod in _STATE_MODULES and a.name in _CTORS:
                    bare.add(a.asname or a.name)
                elif mod == "fabric_tpu" and a.name == "state":
                    aliases.add(a.asname or a.name)
                elif mod == "fabric_tpu.state" and a.name == "residency":
                    aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name in _STATE_MODULES and a.asname:
                    aliases.add(a.asname)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            local_defs.add(node.name)
    return bare - local_defs, aliases


def _is_mgr_ctor(call: ast.Call, bare: set, aliases: set) -> bool:
    name = dotted_name(call.func)
    if name is None:
        return False
    parts = name.split(".")
    if len(parts) == 1:
        return parts[0] in bare
    return parts[0] in aliases and parts[-1] in _CTORS


def _walk_own(scope: ast.AST):
    """A scope's own nodes; nested defs are their own scopes."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _mgr_locals(scope: ast.AST, bare: set, aliases: set) -> set:
    """Local names assigned EXACTLY once in the scope, from a manager
    ctor — a reassigned name has unknown provenance and never counts
    (the under-approximation contract)."""
    assigns: dict[str, int] = {}
    from_ctor: set[str] = set()
    for node in _walk_own(scope):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            name = node.targets[0].id
            assigns[name] = assigns.get(name, 0) + 1
            if (isinstance(node.value, ast.Call)
                    and _is_mgr_ctor(node.value, bare, aliases)):
                from_ctor.add(name)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            t = node.target
            if isinstance(t, ast.Name):
                assigns[t.id] = assigns.get(t.id, 0) + 1
    return {n for n in from_ctor if assigns.get(n) == 1}


def _class_mgr_attrs(cls: ast.ClassDef, bare: set, aliases: set) -> set:
    """self-attr names assigned from a manager ctor anywhere in the
    class's methods."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            continue
        if (isinstance(node.value, ast.Call)
                and _is_mgr_ctor(node.value, bare, aliases)):
            out.add(t.attr)
    return out


def _scan_scope(scope: ast.AST, mgr_recvs: set):
    """→ (writer call lines, hook touched?) over one scope.  A hook
    counts only on a bound manager receiver (a local manager name or
    a ``self.<attr>`` the class assigned from a ctor)."""
    writers: list[int] = []
    hooked = False
    for node in _walk_own(scope):
        if not isinstance(node, ast.Attribute):
            continue
        if node.attr == _WRITER:
            writers.append(node.lineno)
        elif node.attr in _HOOKS:
            recv = dotted_name(node.value)
            if recv is not None and recv in mgr_recvs:
                hooked = True
    return writers, hooked


@register
class ResidentStateBypassRule(Rule):
    id = "FT015"
    name = "resident-state-bypass"
    severity = "error"
    description = (
        "flags committed version-store writes (apply_updates) in a "
        "scope that provably holds a residency manager "
        "(fabric_tpu/state) yet never reaches its coherence hooks "
        "(apply_batch / invalidate_keys / disable) — a stale resident "
        "version silently corrupts MVCC verdicts"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        rel = ctx.relpath
        base = rel.rsplit("/", 1)[-1]
        if ("tests/" in rel or rel.startswith("tests")
                or base.startswith("test_") or base == "conftest.py"):
            return []
        bare, aliases = _bindings(ctx.tree)
        if not bare and not aliases:
            return []  # the module never imports the subsystem
        out: list[Finding] = []

        def check(scope: ast.AST, mgr_recvs: set, where: str):
            if not mgr_recvs:
                return
            writers, hooked = _scan_scope(scope, mgr_recvs)
            if hooked:
                return
            names = ", ".join(sorted(mgr_recvs))
            for line in writers:
                out.append(self.finding(
                    ctx, line, 0,
                    f"committed-store write (apply_updates) in a "
                    f"scope holding a residency manager ({names}, "
                    f"{where}) without reaching its coherence hooks "
                    "— the resident version table keeps serving the "
                    "OLD version after this write lands, silently "
                    "forking MVCC verdicts from the host oracle; "
                    "apply the write-set via <mgr>.apply_batch(batch)"
                    " at the commit boundary, or invalidate_keys/"
                    "disable the cache",
                ))

        # class methods: self-attr managers (local managers inside the
        # method count too); checked scopes are remembered so the
        # function pass below never double-reports a method
        seen: set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = _class_mgr_attrs(node, bare, aliases)
            if not attrs:
                continue
            recvs = {f"self.{a}" for a in attrs}
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    seen.add(id(child))
                    local = _mgr_locals(child, bare, aliases)
                    check(child, recvs | local,
                          f"class {node.name}")
        # plain function scopes (and the module body): local managers
        for scope in [ctx.tree] + list(walk_functions(ctx.tree)):
            if id(scope) in seen:
                continue
            local = _mgr_locals(scope, bare, aliases)
            if not local:
                continue
            where = (
                "module scope" if isinstance(scope, ast.Module)
                else f"function {getattr(scope, 'name', '?')}"
            )
            check(scope, local, where)
        return out

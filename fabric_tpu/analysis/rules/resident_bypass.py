"""FT015 resident-state-bypass: committed-store writes that skip the
residency cache's invalidation hook.

The device-resident MVCC cache (``fabric_tpu/state/residency.py``)
mirrors the committed version store in device memory.  The ONE
coherence rule is that every write to the committed store must reach
the cache — either as the commit-boundary delta scatter
(``ResidencyManager.apply_batch``) or, when the delta is unknown, as
an invalidation (``invalidate_keys`` / ``disable``).  A
``state.apply_updates(...)`` that bypasses the hook leaves a STALE
version resident: the next block's device compare judges reads
against a world that no longer exists — a silent MVCC verdict
corruption, the worst failure class this repo has (verdicts fork from
the host oracle with no error anywhere).

Mechanics (strictly under-approximating, per the FT003..FT014
contract — a finding is always real), on the shared provenance
engine (:mod:`fabric_tpu.analysis.provenance`):

1. **A manager must be provably in hand.**  Two binding shapes count,
   both import-aware (``ImportMap`` — a same-named local helper never
   matches):

   * a single-assignment LOCAL bound from ``ResidencyManager(...)``
     or ``resolve_residency(...)`` (bare from-imports or dotted calls
     through a module alias of ``fabric_tpu.state`` /
     ``fabric_tpu.state.residency``);
   * a SELF-ATTR assigned from one of those ctors anywhere in the
     same class (``self.resident = ResidencyManager(...)``).

   A scope with no visible manager binding never flags — the rule
   polices code that HAS the cache and forgets it, not code that has
   never heard of it.
2. **The writer**: any ``<recv>.apply_updates(...)`` call in that
   scope (the ``VersionedDB`` committed-store writer — the method
   name is specific enough that, combined with rule 1's manager
   requirement, a false pairing requires a same-scope manager AND an
   unrelated ``apply_updates`` — accepted residual risk: zero such
   shapes exist in the repo).
3. **The hook**: the finding is suppressed when the SAME scope also
   touches the manager's coherence family — ``apply_batch``,
   ``invalidate_keys`` or ``disable`` — on a bound manager (local or
   class self-attr).

Test code is exempt engine-wide — differentials drive stale-cache
shapes on purpose.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    dotted_name,
    register,
)
from fabric_tpu.analysis.provenance import (
    class_self_attrs,
    module_index,
    walk_scope,
)

_CTORS = {"ResidencyManager", "resolve_residency"}
_HOOKS = {"apply_batch", "invalidate_keys", "disable"}
_WRITER = "apply_updates"
_STATE_MODULES = ("fabric_tpu.state", "fabric_tpu.state.residency")
#: canonical dotted names of the manager constructors
_CTOR_CANON = {f"{m}.{c}" for m in _STATE_MODULES for c in _CTORS}


def _scan_scope(scope: ast.AST, mgr_recvs: set):
    """→ (writer call lines, hook touched?) over one scope.  A hook
    counts only on a bound manager receiver (a local manager name or
    a ``self.<attr>`` the class assigned from a ctor)."""
    writers: list[int] = []
    hooked = False
    for node in walk_scope(scope):
        if not isinstance(node, ast.Attribute):
            continue
        if node.attr == _WRITER:
            writers.append(node.lineno)
        elif node.attr in _HOOKS:
            recv = dotted_name(node.value)
            if recv is not None and recv in mgr_recvs:
                hooked = True
    return writers, hooked


@register
class ResidentStateBypassRule(Rule):
    id = "FT015"
    name = "resident-state-bypass"
    severity = "error"
    description = (
        "flags committed version-store writes (apply_updates) in a "
        "scope that provably holds a residency manager "
        "(fabric_tpu/state) yet never reaches its coherence hooks "
        "(apply_batch / invalidate_keys / disable) — a stale resident "
        "version silently corrupts MVCC verdicts"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        idx = module_index(ctx)
        imports = idx.imports
        if not imports.any_binding(
            lambda c: c.startswith("fabric_tpu.state")
        ):
            return []  # the module never imports the subsystem
        is_ctor = lambda v: (isinstance(v, ast.Call)
                             and imports.resolve_call(v) in _CTOR_CANON)
        out: list[Finding] = []

        def check(scope: ast.AST, mgr_recvs: set, where: str):
            if not mgr_recvs:
                return
            writers, hooked = _scan_scope(scope, mgr_recvs)
            if hooked:
                return
            names = ", ".join(sorted(mgr_recvs))
            for line in writers:
                out.append(self.finding(
                    ctx, line, 0,
                    f"committed-store write (apply_updates) in a "
                    f"scope holding a residency manager ({names}, "
                    f"{where}) without reaching its coherence hooks "
                    "— the resident version table keeps serving the "
                    "OLD version after this write lands, silently "
                    "forking MVCC verdicts from the host oracle; "
                    "apply the write-set via <mgr>.apply_batch(batch)"
                    " at the commit boundary, or invalidate_keys/"
                    "disable the cache",
                ))

        # class methods: self-attr managers (local managers inside the
        # method count too); checked scopes are remembered so the
        # function pass below never double-reports a method
        seen: set[int] = set()
        for cls in idx.classes:
            attrs = class_self_attrs(cls, is_ctor)
            if not attrs:
                continue
            recvs = {f"self.{a}" for a in attrs}
            for fn in idx.class_methods(cls).values():
                seen.add(id(fn))
                local = idx.scope(fn).names_where(is_ctor)
                check(fn, recvs | local, f"class {cls.name}")
        # plain function scopes (and the module body): local managers
        for scope in [ctx.tree] + idx.functions:
            if id(scope) in seen:
                continue
            local = idx.scope(scope).names_where(is_ctor)
            if not local:
                continue
            where = (
                "module scope" if isinstance(scope, ast.Module)
                else f"function {getattr(scope, 'name', '?')}"
            )
            check(scope, local, where)
        return out

"""FT013 metric-label-cardinality: per-request ids as metric labels.

The metrics registry (fabric_tpu.ops_metrics) materializes one series
per LABEL VARIANT, forever: every distinct label value grows the
exposition (`/metrics` render walks all of them), and — since the
flight-data recorder landed — also one bounded time-series ring per
variant in the sampler.  A label value derived from per-request or
per-loop data (transaction ids, block numbers, request sequence
numbers) therefore makes cardinality unbounded: a day of traffic
turns a counter into millions of dead series.  The label discipline
in this repo is small closed sets — channel, tenant, stage, status,
knob, point, kind — and this rule polices it.

Mechanics (strictly under-approximating, per the FT003..FT012
contract — a finding is always real), on the shared provenance
engine (:mod:`fabric_tpu.analysis.provenance`):

1. **Metric receiver match** — a write call ``<recv>.add(...)`` /
   ``<recv>.set(...)`` / ``<recv>.observe(...)`` counts only when
   ``<recv>`` provably is a registry instrument:

   * a chained constructor call ``<reg>.counter("name", ...)`` /
     ``.gauge(...)`` / ``.histogram(...)`` whose FIRST argument is a
     string literal (every registry registration passes the metric
     name; a same-named method on an unrelated object does not), or
   * a single-assignment local bound from such a constructor call
     (``SingleAssignScope``), or
   * a ``self.<attr>`` assigned from such a constructor call anywhere
     in the same class (``class_self_attrs`` — the repo's
     ``self._ctr = registry.counter`` idiom).

2. **Unbounded label value** — a keyword argument (label) flags only
   when its value expression provably carries per-request identity:

   * an attribute chain ending in ``.txid`` / ``.tx_id``, or
     containing ``header.number`` (the block-number chain), or
   * a bare name exactly ``txid`` / ``tx_id`` / ``request_id`` /
     ``req_id``, or a single-assignment local bound from one of the
     above, or
   * any of those wrapped in ``str()`` / ``int()`` / ``repr()`` /
     ``format()``, an f-string, or a ``%``/``+`` format expression.

   Anything else — loop variables, computed strings, unknown names —
   never flags: the closed-set discipline cannot be proven violated,
   so the rule stays silent (under-approximation).

Test code is exempt engine-wide; suppress a deliberate
bounded-by-construction case with ``# fabtpu: noqa(FT013)`` on the
write line.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    dotted_name,
    register,
)
from fabric_tpu.analysis.provenance import (
    class_self_attrs,
    module_index,
    walk_scope,
)

_CTORS = {"counter", "gauge", "histogram"}
_WRITES = {"add", "set", "observe"}
_BAD_NAMES = {"txid", "tx_id", "request_id", "req_id"}
_BAD_ATTR_TAILS = {"txid", "tx_id"}
_WRAPPERS = {"str", "int", "repr", "format"}


def _is_metric_ctor(call: ast.AST) -> bool:
    """``<reg>.counter("name", ...)``-shaped: attribute call named
    like a registry constructor whose first argument is a string
    literal (the metric name)."""
    return (
        isinstance(call, ast.Call)
        and isinstance(call.func, ast.Attribute)
        and call.func.attr in _CTORS
        and bool(call.args)
        and isinstance(call.args[0], ast.Constant)
        and isinstance(call.args[0].value, str)
    )


def _unbounded_reason(expr: ast.AST, scope, depth: int = 0) -> str | None:
    """Why ``expr`` carries per-request identity, or None."""
    if depth > 3:
        return None
    if isinstance(expr, ast.Name):
        if expr.id in _BAD_NAMES:
            return f"per-request identifier {expr.id!r}"
        src = scope.value_of(expr.id)
        if src is not None:
            return _unbounded_reason(src, scope, depth + 1)
        return None
    if isinstance(expr, ast.Attribute):
        dn = dotted_name(expr)
        if expr.attr in _BAD_ATTR_TAILS:
            return f"per-transaction id {dn or expr.attr!r}"
        if dn is not None and "header.number" in dn:
            return f"per-block number {dn!r}"
        return None
    if isinstance(expr, ast.Call):
        name = dotted_name(expr.func)
        if name in _WRAPPERS and expr.args:
            return _unbounded_reason(expr.args[0], scope, depth + 1)
        return None
    if isinstance(expr, ast.JoinedStr):
        for v in expr.values:
            if isinstance(v, ast.FormattedValue):
                r = _unbounded_reason(v.value, scope, depth + 1)
                if r is not None:
                    return r
        return None
    if isinstance(expr, ast.BinOp):
        return (_unbounded_reason(expr.left, scope, depth + 1)
                or _unbounded_reason(expr.right, scope, depth + 1))
    return None


@register
class MetricLabelCardinalityRule(Rule):
    id = "FT013"
    name = "metric-label-cardinality"
    severity = "error"
    description = (
        "flags Registry counter/gauge/histogram label values derived "
        "from per-request data (txids, block numbers, request ids): "
        "every distinct value materializes a series forever, so "
        "exposition — and the flight-data recorder's per-variant "
        "time-series rings — grow without bound"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        idx = module_index(ctx)
        class_attrs = {
            cls: class_self_attrs(cls, _is_metric_ctor)
            for cls in idx.classes
        }
        out: list[Finding] = []
        for scope_node in [ctx.tree] + idx.functions:
            scope = idx.scope(scope_node)
            metric_locals = scope.names_where(_is_metric_ctor)
            cls = idx.enclosing_class(scope_node)
            self_metrics = class_attrs.get(cls, set()) if cls else set()
            for node in walk_scope(scope_node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _WRITES):
                    continue
                recv = node.func.value
                is_metric = (
                    _is_metric_ctor(recv)
                    or (isinstance(recv, ast.Name)
                        and recv.id in metric_locals)
                    or (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                        and recv.attr in self_metrics)
                )
                if not is_metric:
                    continue
                for kw in node.keywords:
                    if kw.arg is None:
                        continue  # **labels: unresolvable, stay silent
                    reason = _unbounded_reason(kw.value, scope)
                    if reason is None:
                        continue
                    out.append(self.finding(
                        ctx, node.lineno, node.col_offset,
                        f"metric label {kw.arg!r} takes {reason}: "
                        "every distinct value materializes a label "
                        "variant forever (unbounded /metrics "
                        "exposition + one vitals series ring per "
                        "value) — label with a small closed set "
                        "(channel/tenant/stage/status) and put "
                        "per-request ids in trace attrs or logs",
                    ))
        return out

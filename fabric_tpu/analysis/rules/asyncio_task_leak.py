"""FT008 asyncio-task-leak: fire-and-forget tasks that nothing holds.

``asyncio.ensure_future`` / ``asyncio.create_task`` return a Task the
event loop references only WEAKLY: if the caller drops the result, the
task can be garbage-collected mid-flight and dies silently — and even
when it survives, its exception is swallowed at GC time and nothing
cancels it on shutdown (the ROADMAP names this rule: "asyncio task
leaks (ensure_future results never cancelled on stop)").  The repo's
own discipline is a strong-ref set with a done-callback discard
(ordering/node.py ``_bg``) or an attribute the stop path cancels.

Mechanics (import-aware per the FT003/FT007 pattern):

1. **Creation sites** — calls that resolve THROUGH the imports to
   asyncio's task spawners: ``<asyncio alias>.ensure_future/create_task``,
   bare ``ensure_future``/``create_task`` bound by a from-import of
   asyncio (renames included), ``<loop var>.create_task`` where the
   loop var was assigned from ``asyncio.get_event_loop()`` /
   ``get_running_loop()`` / ``new_event_loop()`` in the same scope, and
   the chained ``asyncio.get_event_loop().create_task(...)`` form.  A
   local helper that merely shares the name ``create_task`` never
   matches (the FT003 lesson).
2. **Leak test** — a creation site leaks when its Task is
   (a) an expression statement (the result is discarded outright), or
   (b) assigned to a plain local name that is never LOADED again
   anywhere in the enclosing function (closures included — a nested
   ``finally: t.cancel()`` counts).  Everything else is clean by
   under-approximation: awaiting, returning, ``.cancel()`` /
   ``add_done_callback``, storing on ``self``/a container, passing to
   any call (``gather``, ``tasks.append``) all show up as a Load or a
   non-Name target.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    register,
    walk_functions,
)

_SPAWNERS = {"ensure_future", "create_task"}
_LOOP_GETTERS = {"get_event_loop", "get_running_loop", "new_event_loop"}


def _asyncio_bindings(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(module_aliases, bare_spawner_names) bound from asyncio anywhere
    in the module (imports are commonly function-local in this tree)."""
    aliases: set[str] = set()
    bare: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "asyncio" or a.name.startswith("asyncio."):
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] != "asyncio":
                continue
            for a in node.names:
                if a.name in _SPAWNERS:
                    bare.add(a.asname or a.name)
    return aliases, bare


def _walk_own(fn: ast.AST):
    """A scope's OWN statements (nested defs/lambdas are their own
    scopes via walk_functions — descending would double-count)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_loop_getter_call(node: ast.AST, aliases: set[str]) -> bool:
    """True for a DIRECT ``asyncio.get_event_loop()``-style call;
    loop-var aliasing (``loop2 = loop``) is deliberately not chased —
    under-approximation keeps false positives at zero."""
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    if name is None:
        return False
    parts = name.split(".")
    return (len(parts) == 2 and parts[0] in aliases
            and parts[1] in _LOOP_GETTERS)


def _spawner_call(node: ast.Call, aliases: set[str], bare: set[str],
                  loop_vars: set[str]) -> bool:
    """True when this Call spawns an asyncio Task, resolved through
    the module's imports."""
    name = call_name(node)
    if name is not None:
        parts = name.split(".")
        if len(parts) == 1:
            return parts[0] in bare
        if parts[-1] not in _SPAWNERS:
            return False
        if parts[0] in aliases and len(parts) == 2:
            return True  # asyncio.ensure_future(...)
        return len(parts) == 2 and parts[0] in loop_vars
    # chained form: asyncio.get_event_loop().create_task(...)
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "create_task"
            and _is_loop_getter_call(f.value, aliases))


@register
class AsyncioTaskLeakRule(Rule):
    id = "FT008"
    name = "asyncio-task-leak"
    severity = "error"
    description = (
        "flags ensure_future/create_task results that are discarded or "
        "bound to a name never used again — unreferenced tasks can be "
        "GC'd mid-flight, lose their exceptions, and are never "
        "cancelled on stop"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        aliases, bare = _asyncio_bindings(ctx.tree)
        if not (aliases or bare):
            return []
        out: list[Finding] = []
        scopes = [ctx.tree] + list(walk_functions(ctx.tree))
        for fn in scopes:
            # loop vars assigned from a loop getter in THIS scope
            loop_vars: set[str] = set()
            for node in _walk_own(fn):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and _is_loop_getter_call(node.value, aliases)):
                    loop_vars.add(node.targets[0].id)
            # names LOADED anywhere under this scope's subtree (incl.
            # closures — a nested `finally: t.cancel()` keeps t alive;
            # for the module scope this is the whole module)
            loads: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load):
                    loads.add(node.id)
            for node in _walk_own(fn):
                if isinstance(node, ast.Expr) and isinstance(
                        node.value, ast.Call) and _spawner_call(
                        node.value, aliases, bare, loop_vars):
                    out.append(self.finding(
                        ctx, node.lineno, node.col_offset,
                        "the Task returned by "
                        f"{call_name(node.value) or 'create_task'} is "
                        "discarded — the loop holds tasks weakly, so it "
                        "can be GC'd mid-flight and its exception is "
                        "lost; keep a strong reference (a set with "
                        "add_done_callback(discard)) and cancel it on "
                        "stop, or await it",
                    ))
                elif (isinstance(node, ast.Assign)
                      and len(node.targets) == 1
                      and isinstance(node.targets[0], ast.Name)
                      and isinstance(node.value, ast.Call)
                      and _spawner_call(node.value, aliases, bare,
                                        loop_vars)):
                    tgt = node.targets[0].id
                    if tgt not in loads:
                        out.append(self.finding(
                            ctx, node.lineno, node.col_offset,
                            f"the Task bound to '{tgt}' is never "
                            "awaited, stored, or cancelled — store a "
                            "strong reference the stop path cancels "
                            "(or add_done_callback + a task set); an "
                            "unreferenced task dies silently at GC",
                        ))
        return out

"""FT003 host-sync-in-hot-path: device syncs on the commit path.

The validator pipeline earns its throughput by keeping exactly ONE
host-device sync per block (the packed stage-2 readback).  Any stray
``.block_until_ready()`` / ``jax.device_get`` / ``.item()`` / direct
``np.asarray(<call>)`` readback inside the commit call graph
serializes the pipeline and shows up only as a bench regression.

The rule builds a project-wide call graph (name-based resolution:
``x.foo()`` and ``foo()`` both link to every ``foo`` definition in the
analyzed set — deliberately over-approximate, never under) rooted at
the functions of ``peer/validator.py`` and ``peer/coordinator.py``,
and flags sync constructs in every reachable function.  Intended sync
points carry a ``# fabtpu: noqa(FT003)`` with a comment saying why.
"""

from __future__ import annotations

import ast
from collections import deque

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    dotted_name,
    register,
    walk_functions,
)

_ROOT_MODULES = ("peer/validator.py", "peer/coordinator.py")

_SYNC_ATTRS = {"block_until_ready", "item"}
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_READBACK_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray",
                        "numpy.array"}
# builtins whose result is host memory by construction — converting
# them is a copy at worst, never a device sync
_HOST_PRODUCERS = {
    "sorted", "list", "tuple", "set", "dict", "range", "zip", "len",
    "enumerate", "min", "max", "sum", "reversed",
}


def _fn_key(mod: ModuleCtx, fn: ast.FunctionDef) -> tuple[str, str, int]:
    # lineno disambiguates same-named methods on different classes
    return (mod.relpath, fn.name, fn.lineno)


@register
class HostSyncRule(Rule):
    id = "FT003"
    name = "host-sync-in-hot-path"
    severity = "error"
    description = (
        "flags device syncs (block_until_ready/device_get/.item()/"
        "np.asarray(<call>)) reachable from the validator/commit graph"
    )
    # overridable in tests
    root_modules: tuple[str, ...] = _ROOT_MODULES
    # how many root functions the last check_project seeded the BFS
    # with — tests pin this > 0 over fabric_tpu/ so a rename of the
    # root modules cannot silently turn the rule into a no-op
    last_root_count: int = 0

    def check_project(self, modules: list[ModuleCtx]) -> list[Finding]:
        # 1. collect every function def, keyed by bare name
        defs: dict[tuple, ast.FunctionDef] = {}
        by_name: dict[str, list[tuple]] = {}
        mod_of: dict[tuple, ModuleCtx] = {}
        for mod in modules:
            for fn in walk_functions(mod.tree):
                key = _fn_key(mod, fn)
                defs[key] = fn
                mod_of[key] = mod
                by_name.setdefault(fn.name, []).append(key)

        # 2. edges: function → called bare names
        calls_of: dict[tuple, set[str]] = {}
        for key, fn in defs.items():
            called: set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    if name:
                        called.add(name.split(".")[-1])
            calls_of[key] = called

        # 3. BFS from the root modules' functions
        roots = [
            key for key, mod in mod_of.items()
            if any(mod.relpath.endswith(r) for r in self.root_modules)
        ]
        self.last_root_count = len(roots)
        hot: set[tuple] = set(roots)
        queue = deque(roots)
        while queue:
            key = queue.popleft()
            for bare in calls_of.get(key, ()):
                for callee in by_name.get(bare, ()):
                    if callee not in hot:
                        hot.add(callee)
                        queue.append(callee)

        # 4. flag sync constructs inside hot functions
        out: list[Finding] = []
        seen: set[tuple] = set()
        for key in hot:
            fn, mod = defs[key], mod_of[key]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._sync_message(node, fn.name)
                if msg is None:
                    continue
                fkey = (mod.relpath, node.lineno, node.col_offset)
                if fkey in seen:
                    continue
                seen.add(fkey)
                out.append(self.finding(
                    mod, node.lineno, node.col_offset, msg,
                ))
        return out

    @staticmethod
    def _sync_message(node: ast.Call, fname: str) -> str | None:
        name = call_name(node)
        if name in _SYNC_CALLS:
            return (
                f"'{name}' in '{fname}' is reachable from the "
                f"validator/commit graph — a host-device sync on the "
                f"hot path"
            )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_ATTRS
            and not node.args and not node.keywords
        ):
            base = dotted_name(node.func.value) or "<expr>"
            return (
                f"'.{node.func.attr}()' on '{base}' in '{fname}' is "
                f"reachable from the validator/commit graph — a "
                f"host-device sync on the hot path"
            )
        if (
            name in _READBACK_CONVERTERS
            and node.args and isinstance(node.args[0], ast.Call)
            and call_name(node.args[0]) not in _HOST_PRODUCERS
        ):
            inner = call_name(node.args[0]) or "<call>"
            return (
                f"'{name}({inner}(...))' in '{fname}' converts a fresh "
                f"call result to host memory on the validator/commit "
                f"graph — a device readback unless proven host-only"
            )
        return None

"""FT003 host-sync-in-hot-path: device syncs on the commit path.

The validator pipeline earns its throughput by keeping exactly ONE
host-device sync per block (the packed stage-2 readback).  Any stray
``.block_until_ready()`` / ``jax.device_get`` / ``.item()`` / direct
``np.asarray(<call>)`` readback inside the commit call graph
serializes the pipeline and shows up only as a bench regression.

The rule builds a project-wide call graph rooted at the functions of
``peer/validator.py`` and ``peer/coordinator.py`` and flags sync
constructs in every reachable function.  Resolution is IMPORT-AWARE:

* ``p256.verify_host()`` where ``p256`` was imported from an analyzed
  module links only to THAT module's ``verify_host`` def — not to
  every same-named def in the project;
* ``from mod import foo`` (incl. ``as`` renames and relative imports,
  collected from function bodies too) links a bare ``foo()`` call only
  to ``mod``'s def;
* calls through names imported from clearly-EXTERNAL modules (numpy,
  jax, stdlib — nothing analyzed shares their root package) produce no
  edges at all;
* anything unresolvable (``self.foo()``, locals, project-looking
  imports that did not resolve) falls back to bare-name linking —
  deliberately over-approximate, never under.

Intended sync points carry a ``# fabtpu: noqa(FT003)`` with a comment
saying why.
"""

from __future__ import annotations

import ast
from collections import deque

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    dotted_name,
    register,
    walk_functions,
)

_ROOT_MODULES = ("peer/validator.py", "peer/coordinator.py")

_SYNC_ATTRS = {"block_until_ready", "item"}
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_READBACK_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray",
                        "numpy.array"}
# builtins whose result is host memory by construction — converting
# them is a copy at worst, never a device sync
_HOST_PRODUCERS = {
    "sorted", "list", "tuple", "set", "dict", "range", "zip", "len",
    "enumerate", "min", "max", "sum", "reversed",
}


def _fn_key(mod: ModuleCtx, fn: ast.FunctionDef) -> tuple[str, str, int]:
    # lineno disambiguates same-named methods on different classes
    return (mod.relpath, fn.name, fn.lineno)


def _dotted_of(relpath: str) -> str:
    """Module relpath → dotted form ("fabric_tpu/ops/p256.py" →
    "fabric_tpu.ops.p256"; packages drop the __init__ leaf)."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class _ModuleIndex:
    """Resolves import dotted paths to analyzed module relpaths.

    Matching is suffix-tolerant in both directions because the
    analysis root is not necessarily the import root: analyzing from
    the repo root gives dotted forms like ``fabric_tpu.ops.p256``
    while analyzing the package directory gives ``ops.p256`` — both
    must resolve ``from fabric_tpu.ops import p256``."""

    def __init__(self, modules: list[ModuleCtx]):
        self._dotted = [(_dotted_of(m.relpath), m.relpath)
                        for m in modules]
        # package segments of the analyzed set: imports sharing none
        # of these are clearly external.  The analysis ROOT's own
        # directory name rides along because absolute imports name the
        # super-package even when the root IS the package directory
        # (root=fabric_tpu/ gives dotted forms like "ops.p256", yet
        # code says "from fabric_tpu.ops import p256" — without this,
        # an unresolvable absolute project import would be classified
        # external and silently under-approximate the graph).
        self.roots = set()
        for d, _ in self._dotted:
            self.roots.update(d.split("."))
        import os

        for m in modules:
            if m.path != m.relpath and m.path.endswith(m.relpath):
                root_dir = m.path[: -len(m.relpath)].rstrip("/\\")
                base = os.path.basename(root_dir)
                if base:
                    self.roots.add(base)

    def resolve(self, dotted: str) -> list[str]:
        if not dotted:
            return []
        out = []
        for d, rel in self._dotted:
            if d == dotted or d.endswith("." + dotted) or \
                    dotted.endswith("." + d):
                out.append(rel)
        return out

    def maybe_project(self, dotted: str) -> bool:
        return bool(dotted) and dotted.split(".")[0] in self.roots


# alias-entry shapes:
#   ("mod", rel)           alias IS analyzed module rel (attr calls link there)
#   ("obj", rel, name)     alias is object `name` imported from module rel;
#                          degrades to bare-name when rel has no such def
#                          (package re-exports must not blind the graph)
#   ("objsoft", rel, name) same, but only a hedge beside a real submodule
#                          match — links iff the def exists, never degrades
#   ("prefix",)            plain `import a.b` — re-resolve from the call's
#                          full dotted path at edge time
#   ("any",)               project-looking but unresolved → bare fallback
# an alias mapping to [] is a KNOWN-external import → no edges at all


def _pkg_parts(relpath: str) -> list[str]:
    parts = relpath.split("/")[:-1]
    if relpath.endswith("/__init__.py"):
        parts = parts[:-1]
    return parts


def _import_aliases(mod: ModuleCtx, index: _ModuleIndex) -> dict:
    """name → alias entries, from every import statement in the module
    (function-local imports included — this codebase imports lazily on
    hot paths)."""
    aliases: dict[str, list] = {}

    def add(name: str, entries: list) -> None:
        aliases.setdefault(name, []).extend(entries)

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    rels = index.resolve(a.name)
                    if rels:
                        add(a.asname, [("mod", r) for r in rels])
                    elif index.maybe_project(a.name):
                        add(a.asname, [("any",)])
                    else:
                        aliases.setdefault(a.asname, [])
                else:
                    head = a.name.split(".")[0]
                    if index.maybe_project(a.name):
                        add(head, [("prefix",)])
                    else:
                        aliases.setdefault(head, [])
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            relative = node.level > 0
            if relative:
                parts = _pkg_parts(mod.relpath)
                if node.level > 1:
                    parts = parts[: -(node.level - 1)] or parts[:1]
                base = ".".join(parts + ([node.module] if node.module
                                         else []))
            mod_rels = index.resolve(base)
            projecty = relative or index.maybe_project(base)
            for a in node.names:
                if a.name == "*":
                    continue
                local = a.asname or a.name
                sub_rels = index.resolve(f"{base}.{a.name}" if base
                                         else a.name)
                entries = [("mod", r) for r in sub_rels]
                # the imported name may be an object in the package
                # module instead of (or shadowing) a submodule; when a
                # submodule DID match, the object entry is only a soft
                # hedge — it must not degrade resolution if the
                # package has no such def
                kind = "objsoft" if sub_rels else "obj"
                entries += [(kind, r, a.name) for r in mod_rels]
                if entries:
                    add(local, entries)
                elif projecty:
                    add(local, [("any",)])
                else:
                    aliases.setdefault(local, [])
    return aliases


@register
class HostSyncRule(Rule):
    id = "FT003"
    name = "host-sync-in-hot-path"
    severity = "error"
    description = (
        "flags device syncs (block_until_ready/device_get/.item()/"
        "np.asarray(<call>)) reachable from the validator/commit graph"
    )
    # overridable in tests
    root_modules: tuple[str, ...] = _ROOT_MODULES
    # how many root functions the last check_project seeded the BFS
    # with — tests pin this > 0 over fabric_tpu/ so a rename of the
    # root modules cannot silently turn the rule into a no-op
    last_root_count: int = 0

    def check_project(self, modules: list[ModuleCtx]) -> list[Finding]:
        index = _ModuleIndex(modules)

        # 1. collect every function def, keyed by bare name and by
        #    (module, name) for import-resolved edges
        defs: dict[tuple, ast.FunctionDef] = {}
        by_name: dict[str, list[tuple]] = {}
        by_mod_name: dict[tuple[str, str], list[tuple]] = {}
        mod_of: dict[tuple, ModuleCtx] = {}
        for mod in modules:
            for fn in walk_functions(mod.tree):
                key = _fn_key(mod, fn)
                defs[key] = fn
                mod_of[key] = mod
                by_name.setdefault(fn.name, []).append(key)
                by_mod_name.setdefault((mod.relpath, fn.name), []).append(key)

        # 2. edges: function → resolution targets
        #    ("name", bare) links every same-named def;
        #    ("mod", rel, bare) links only rel's defs
        alias_cache: dict[str, dict] = {}

        def targets_of(mod: ModuleCtx, name: str) -> list[tuple]:
            aliases = alias_cache.get(mod.relpath)
            if aliases is None:
                aliases = alias_cache[mod.relpath] = _import_aliases(
                    mod, index
                )
            bare = name.split(".")[-1]
            head = name.split(".")[0]
            is_attr = "." in name
            if head not in aliases:
                return [("name", bare)]

            def resolved(rel: str, nm: str) -> tuple:
                # a resolved module WITHOUT a def of that name means
                # the name is re-exported (`__init__` facades) or
                # synthesized — degrade to bare-name rather than drop
                # the edge: over-approximate, never under
                if (rel, nm) in by_mod_name:
                    return ("mod", rel, nm)
                return ("name", nm)

            out: list[tuple] = []
            for entry in aliases[head]:
                kind = entry[0]
                if kind == "mod":
                    # bare call of a module name is not a function
                    # call; the companion ("obj") entry covers the
                    # imported-class case
                    if is_attr:
                        out.append(resolved(entry[1], bare))
                elif kind == "obj":
                    # attr call through an imported class/object: its
                    # methods live where the object is defined
                    out.append(
                        resolved(entry[1], bare if is_attr else entry[2])
                    )
                elif kind == "objsoft":
                    # hedge beside a real submodule match: link only
                    # when the package module actually defines the
                    # name, never degrade through it
                    nm = bare if is_attr else entry[2]
                    if (entry[1], nm) in by_mod_name:
                        out.append(("mod", entry[1], nm))
                elif kind == "prefix" and is_attr:
                    dotted = name.rsplit(".", 1)[0]
                    rels = index.resolve(dotted)
                    if rels:
                        out.extend(resolved(r, bare) for r in rels)
                    elif index.maybe_project(dotted):
                        return [("name", bare)]
                elif kind == "any":
                    return [("name", bare)]
            # a local def can shadow an import — keep the same-module
            # edge so added precision can never drop a real callee
            out.append(("mod", mod.relpath, bare))
            return out

        calls_of: dict[tuple, list[tuple]] = {}
        for key, fn in defs.items():
            mod = mod_of[key]
            seen: set[tuple] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    if name:
                        seen.update(targets_of(mod, name))
            calls_of[key] = list(seen)

        # 3. BFS from the root modules' functions
        roots = [
            key for key, mod in mod_of.items()
            if any(mod.relpath.endswith(r) for r in self.root_modules)
        ]
        self.last_root_count = len(roots)
        hot: set[tuple] = set(roots)
        queue = deque(roots)
        while queue:
            key = queue.popleft()
            for target in calls_of.get(key, ()):
                if target[0] == "name":
                    callees = by_name.get(target[1], ())
                else:
                    callees = by_mod_name.get((target[1], target[2]), ())
                for callee in callees:
                    if callee not in hot:
                        hot.add(callee)
                        queue.append(callee)

        # 4. flag sync constructs inside hot functions
        out: list[Finding] = []
        seen_f: set[tuple] = set()
        for key in hot:
            fn, mod = defs[key], mod_of[key]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._sync_message(node, fn.name)
                if msg is None:
                    continue
                fkey = (mod.relpath, node.lineno, node.col_offset)
                if fkey in seen_f:
                    continue
                seen_f.add(fkey)
                out.append(self.finding(
                    mod, node.lineno, node.col_offset, msg,
                ))
        return out

    @staticmethod
    def _sync_message(node: ast.Call, fname: str) -> str | None:
        name = call_name(node)
        if name in _SYNC_CALLS:
            return (
                f"'{name}' in '{fname}' is reachable from the "
                f"validator/commit graph — a host-device sync on the "
                f"hot path"
            )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_ATTRS
            and not node.args and not node.keywords
        ):
            base = dotted_name(node.func.value) or "<expr>"
            return (
                f"'.{node.func.attr}()' on '{base}' in '{fname}' is "
                f"reachable from the validator/commit graph — a "
                f"host-device sync on the hot path"
            )
        if (
            name in _READBACK_CONVERTERS
            and node.args and isinstance(node.args[0], ast.Call)
            and call_name(node.args[0]) not in _HOST_PRODUCERS
        ):
            inner = call_name(node.args[0]) or "<call>"
            return (
                f"'{name}({inner}(...))' in '{fname}' converts a fresh "
                f"call result to host memory on the validator/commit "
                f"graph — a device readback unless proven host-only"
            )
        return None

"""FT007 kernel-dtype-mismatch: 64-bit host arrays crossing into
32-bit kernel lanes.

The device kernels under ``ops/`` take int32 lane arrays (key ids,
window digits, packed launch vectors): XLA truncates or type-errors
far from the call site when a caller hands them a default-dtype numpy
array (``np.arange`` / ``np.bincount`` / ``np.full(..., np.int64)``
are int64 on every 64-bit platform).  The ROADMAP names this the next
rule worth having: "ops/ callers passing int64 into int32 lanes".

Mechanics (project rule, two passes):

1. **Lane declarations** — functions in ``ops/`` modules declare their
   lane dtypes with the repo's existing convention: a trailing comment
   on the parameter's own line (``read_keys,  # [T, R] int32``) or a
   docstring line starting with the parameter name that names a dtype
   (``w1, w2: [B, 64] int32 ...``).  Parameters declaring ``int32`` /
   ``i32`` / ``uint32`` become checked lanes.
2. **Call sites** — every analyzed module is scanned for calls that
   RESOLVE to a declared kernel through its imports (the FT003
   discipline, scaled down): a bare name bound by a ``from``-import of
   an ops module, or an ``alias.func`` attribute call whose alias was
   imported from/under ``ops`` — a local helper that merely shares a
   kernel's name never matches.  Arguments whose dtype is STATICALLY
   known 64-bit — ``np.zeros/ones/empty/full/array/asarray`` with an
   explicit ``int64``/``float64`` dtype, ``.astype(np.int64)``, or
   dtype-less ``np.arange`` (platform int64) — directly or through a
   single local assignment, are flagged when they land in a 32-bit
   lane.

Unknown dtypes are never flagged (the rule under-approximates), so the
battery stays quiet on slices, gathers, and anything the AST cannot
type.  Scanning is per-SCOPE (nested defs are walked as their own
functions, not re-visited from the enclosing one), so a call inside a
staging closure yields exactly one finding.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    register,
    walk_functions,
)

_LANE32_RE = re.compile(r"\b(?:u?int32|[iu]32)\b")
_DTYPE64 = {"int64", "float64", "longlong", "double"}
_DTYPE_OK = {
    "int32", "i32", "uint32", "u32", "int16", "int8", "uint8", "uint16",
    "bool", "bool_", "float32", "bfloat16",
}
_CTOR_WITH_DTYPE = {
    # basename → positional index of the dtype argument
    "zeros": 1, "ones": 1, "empty": 1, "full": 2, "array": 1,
    "asarray": 1, "arange": 3, "fromiter": 1,
}


def _dtype_name(node: ast.AST) -> str | None:
    """``np.int64`` / ``jnp.int64`` / ``'int64'`` → 'int64'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _expr_dtype(node: ast.AST) -> str | None:
    """Statically-known numpy dtype of an expression, or None."""
    if isinstance(node, ast.Subscript):
        # slicing/gathers preserve dtype: np.arange(n)[:, None]
        return _expr_dtype(node.value)
    if isinstance(node, ast.Call):
        name = call_name(node) or ""
        base = name.split(".")[-1]
        if base == "astype" or (
            isinstance(node.func, ast.Attribute) and node.func.attr == "astype"
        ):
            if node.args:
                return _dtype_name(node.args[0])
            for kw in node.keywords:
                if kw.arg == "dtype":
                    return _dtype_name(kw.value)
            return None
        if base in _CTOR_WITH_DTYPE:
            for kw in node.keywords:
                if kw.arg == "dtype":
                    return _dtype_name(kw.value)
            pos = _CTOR_WITH_DTYPE[base]
            if len(node.args) > pos:
                got = _dtype_name(node.args[pos])
                if got is not None:
                    return got
            if base in ("arange",):
                # dtype-less arange over ints is platform int64 — the
                # exact hazard this rule exists for
                return "int64"
            return None
    return None


class _LaneDecl:
    __slots__ = ("params", "order")

    def __init__(self):
        self.params: dict[str, str] = {}  # name → declared dtype text
        self.order: list[str] = []


def _walk_own(fn: ast.AST):
    """Walk a function's OWN body: yields nodes without descending into
    nested function/class scopes (those are visited as their own
    functions by walk_functions — descending here would double-count
    their calls and mix scopes' dtype environments)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_ops_module(dotted: str | None) -> bool:
    """'fabric_tpu.ops.mvcc' / 'ops.p256v3' / '..ops' → True."""
    return dotted is not None and "ops" in dotted.split(".")


def _ops_bindings(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(module_aliases, bare_names) bound from ops modules anywhere in
    the module (imports are commonly function-local in this tree)."""
    aliases: set[str] = set()
    bare: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if _is_ops_module(a.name):
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                # from fabric_tpu.ops import mvcc as mvcc_ops →
                # alias; from fabric_tpu.ops.mvcc import f → bare name
                if _is_ops_module(f"{mod}.{a.name}"):
                    aliases.add(a.asname or a.name)
                if _is_ops_module(mod):
                    bare.add(a.asname or a.name)
    return aliases, bare


def _comment_map(source: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass
    return out


def _collect_kernels(ctx: ModuleCtx) -> dict[str, _LaneDecl]:
    """Lane declarations for one ops/ module's top-level functions."""
    comments = _comment_map(ctx.source)
    out: dict[str, _LaneDecl] = {}
    for node in ctx.tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        doc = ast.get_docstring(node) or ""
        doc_lines = [ln.strip() for ln in doc.splitlines()]
        decl = _LaneDecl()
        args = node.args.posonlyargs + node.args.args
        for a in args:
            decl.order.append(a.arg)
            txt = comments.get(a.lineno, "")
            if _LANE32_RE.search(txt):
                decl.params[a.arg] = "int32"
                continue
            for ln in doc_lines:
                if ln.startswith(a.arg) and _LANE32_RE.search(ln):
                    decl.params[a.arg] = "int32"
                    break
        if decl.params:
            out[node.name] = decl
    return out


@register
class KernelDtypeMismatchRule(Rule):
    id = "FT007"
    name = "kernel-dtype-mismatch"
    severity = "error"
    description = (
        "flags statically-known int64/float64 arrays passed into "
        "int32-declared lanes of ops/ kernel functions"
    )

    def check_project(self, modules: list[ModuleCtx]) -> list[Finding]:
        kernels: dict[str, _LaneDecl] = {}
        for ctx in modules:
            parts = ctx.relpath.split("/")
            if "ops" in parts[:-1]:
                kernels.update(_collect_kernels(ctx))
        if not kernels:
            return []

        out: list[Finding] = []
        for ctx in modules:
            aliases, bare = _ops_bindings(ctx.tree)
            if not (aliases or bare):
                continue  # module never imports from ops
            for fn in walk_functions(ctx.tree):
                env: dict[str, str] = {}  # local var → known dtype
                for node in _walk_own(fn):
                    if isinstance(node, ast.Assign) and len(
                            node.targets) == 1 and isinstance(
                            node.targets[0], ast.Name):
                        dt = _expr_dtype(node.value)
                        name = node.targets[0].id
                        if dt is not None:
                            env[name] = dt
                        else:
                            env.pop(name, None)  # reassigned: unknown
                for node in _walk_own(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    cname = call_name(node) or ""
                    parts = cname.split(".")
                    base = parts[-1]
                    decl = kernels.get(base)
                    if decl is None:
                        continue
                    # import-aware gate: a bare call must be an
                    # ops from-import; a dotted call's root must be
                    # an ops-module alias (a same-named local helper
                    # never matches — the FT003 lesson)
                    if len(parts) == 1:
                        if base not in bare:
                            continue
                    elif parts[0] not in aliases:
                        continue
                    bound: list[tuple[str, ast.AST]] = []
                    for i, arg in enumerate(node.args):
                        if isinstance(arg, ast.Starred):
                            break  # positions unknowable past a star
                        if i < len(decl.order):
                            bound.append((decl.order[i], arg))
                    for kw in node.keywords:
                        if kw.arg is not None:
                            bound.append((kw.arg, kw.value))
                    for pname, arg in bound:
                        if pname not in decl.params:
                            continue
                        dt = _expr_dtype(arg)
                        if dt is None and isinstance(arg, ast.Name):
                            dt = env.get(arg.id)
                        if dt in _DTYPE64:
                            out.append(self.finding(
                                ctx, arg.lineno, arg.col_offset,
                                f"argument '{pname}' of kernel "
                                f"'{base}' is declared int32 but the "
                                f"caller passes a known {dt} array — "
                                f"cast with .astype(np.int32) at the "
                                f"boundary (np.arange/bincount default "
                                f"to int64 on 64-bit hosts)",
                            ))
        return out

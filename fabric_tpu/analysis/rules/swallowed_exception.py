"""FT005 swallowed-exception: broad except that drops the error.

A bare ``except:`` / ``except Exception:`` whose body neither raises,
logs, references the caught exception, nor translates it into a
result value is a silent failure: on the commit path it turns a
deterministic bug into a block that "just didn't commit".  Handlers
that return an explicit value (``return False`` / ``return None`` —
a sentinel the caller dispatches on), assign a fallback, or log are
fine — the rule only fires on pure drops (``pass`` / ``continue`` /
bare ``return``).
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    register,
)

_BROAD = {"Exception", "BaseException"}
_LOGGY = ("log", "warn", "print", "exception", "debug", "error", "info")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(e, "id", getattr(e, "attr", "")) for e in t.elts]
    else:
        names = [getattr(t, "id", getattr(t, "attr", ""))]
    return any(n in _BROAD for n in names)


def _drops_error(handler: ast.ExceptHandler) -> bool:
    """True when the handler body is a pure drop."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            name = (call_name(node) or "").lower()
            attr = (
                node.func.attr.lower()
                if isinstance(node.func, ast.Attribute) else ""
            )
            if any(k in name or k in attr for k in _LOGGY):
                return False
        if handler.name and isinstance(node, ast.Name) and (
                node.id == handler.name):
            return False
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return):
            if stmt.value is None:
                continue  # bare `return`: a drop
            # ANY explicit value — including a written-out `return
            # None` — is a deliberate sentinel the caller dispatches on
            return False
        if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant):
            continue  # docstring / ellipsis
        return False  # any other statement counts as handling
    return True


@register
class SwallowedExceptionRule(Rule):
    id = "FT005"
    name = "swallowed-exception"
    severity = "error"
    description = (
        "flags bare/broad except handlers whose body drops the error "
        "without raising, logging, or producing a verdict"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if _is_broad(handler) and _drops_error(handler):
                    what = (
                        "bare except" if handler.type is None
                        else "broad except"
                    )
                    out.append(self.finding(
                        ctx, handler.lineno, handler.col_offset,
                        f"{what} swallows the error — no raise, no "
                        f"log, no verdict; failures become silent",
                    ))
        return out

"""FT006 union-env-coercion: env strings reaching non-scalar unions.

The exact ADVICE round-5 bug class: an env-override loop that walks
``dataclasses.fields(cfg)``, filters on "scalar or union", and hands
the raw env STRING to a coercer.  For ``Optional[int]`` that's fine;
for ``Optional[TlsConfig]`` the coercer has no scalar branch and the
string passes through untouched — ``cfg.tls`` becomes a ``str`` and
crashes far away with ``AttributeError`` instead of a ``ConfigError``
naming the key.

Detection is structural: a function that (a) reads an environ
mapping, (b) iterates ``dataclasses.fields(...)``, and (c) calls
``setattr`` is an env-override loop.  If that function never inspects
the union's argument types (no ``typing.get_args`` call anywhere in
its body), every ``Optional[<non-scalar>]`` field of the module's
dataclasses is a coercion hazard and gets flagged.  Adding the
``get_args``-based scalar guard (or dropping union handling) clears
the rule.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    dotted_name,
    register,
    walk_functions,
)

_SCALARS = {"int", "float", "str", "bool"}


def _reads_environ(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        name = dotted_name(node) if isinstance(
            node, (ast.Name, ast.Attribute)) else None
        if name in ("os.environ", "environ") or (
            isinstance(node, ast.Call)
            and call_name(node) in ("os.getenv",)
        ):
            return True
    return False


def _iterates_fields(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, (ast.For, ast.comprehension)):
            it = node.iter
            if isinstance(it, ast.Call) and (
                call_name(it) in ("dataclasses.fields", "fields")
            ):
                return True
    return False


def _calls(fn: ast.AST, names: tuple[str, ...]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            cname = call_name(node) or ""
            if cname.split(".")[-1] in names:
                return True
    return False


def _union_nonscalar(annotation: ast.AST) -> str | None:
    """'X | None' / 'Optional[X]' with non-scalar X → X's name."""
    # PEP 604: X | None
    if isinstance(annotation, ast.BinOp) and isinstance(
            annotation.op, ast.BitOr):
        parts = _flatten_bitor(annotation)
        names = [dotted_name(p) or _const_name(p) for p in parts]
        non_none = [n for n in names if n and n != "None"]
        if len(non_none) == 1 and non_none[0].split(".")[-1] not in _SCALARS:
            return non_none[0]
        return None
    # Optional[X] / Union[X, None]
    if isinstance(annotation, ast.Subscript):
        base = dotted_name(annotation.value) or ""
        if base.split(".")[-1] not in ("Optional", "Union"):
            return None
        sl = annotation.slice
        elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
        names = [dotted_name(e) or _const_name(e) for e in elts]
        non_none = [n for n in names if n and n != "None"]
        if len(non_none) == 1 and non_none[0].split(".")[-1] not in _SCALARS:
            return non_none[0]
    return None


def _flatten_bitor(node: ast.BinOp) -> list[ast.AST]:
    out: list[ast.AST] = []
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, ast.BinOp) and isinstance(cur.op, ast.BitOr):
            stack.extend([cur.left, cur.right])
        else:
            out.append(cur)
    return out


def _const_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant):
        if node.value is None:
            return "None"
        if isinstance(node.value, str):
            # string annotation: good enough for a name match
            return node.value
    return None


def _is_dataclass(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        name = dotted_name(dec) or (
            dotted_name(dec.func) if isinstance(dec, ast.Call) else None
        )
        if name and name.split(".")[-1] == "dataclass":
            return True
    return False


@register
class UnionEnvCoercionRule(Rule):
    id = "FT006"
    name = "union-env-coercion"
    severity = "error"
    description = (
        "flags Optional[non-scalar] dataclass fields reachable from "
        "an env-override loop that never inspects union args"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        # env-override loops that hand field types to a coercer
        # without a get_args-based scalar guard
        unguarded: list[str] = []
        for fn in walk_functions(ctx.tree):
            if not (
                _reads_environ(fn)
                and _iterates_fields(fn)
                and _calls(fn, ("setattr",))
            ):
                continue
            if not _calls(fn, ("get_args",)):
                unguarded.append(fn.name)
        if not unguarded:
            return []

        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef) or not _is_dataclass(node):
                continue
            for stmt in node.body:
                if not isinstance(stmt, ast.AnnAssign) or not isinstance(
                        stmt.target, ast.Name):
                    continue
                inner = _union_nonscalar(stmt.annotation)
                if inner is None:
                    continue
                out.append(self.finding(
                    ctx, stmt.lineno, stmt.col_offset,
                    f"field '{node.name}.{stmt.target.id}' is "
                    f"Optional[{inner}] and env loop "
                    f"'{unguarded[0]}' coerces union fields without "
                    f"checking the union's args are scalar — an env "
                    f"string would be assigned raw",
                ))
        return out

"""FT016 unattributed-device-sync: device syncs that bypass the
launch ledger.

The launch ledger (``fabric_tpu/observe/ledger.py``) is only as
honest as its coverage: every device sync inside ``fabric_tpu/`` hot
paths must run inside a :class:`LaunchRecord` bracket
(``sync_begin``/``sync_end``), or the wall it spends is invisible to
the compile/queue/execute/transfer decomposition — BENCH attribution
and the autopilot's ``device_queue_ms`` signal silently under-read
device pressure.  This rule flags sync constructs that PROVABLY
bypass the wrapper.

Mechanics (strictly under-approximating, per the FT003..FT015
contract — a finding is always real):

1. **A sync construct**, one of:

   * ``jax.device_get(...)`` / ``jax.block_until_ready(...)`` —
     import-aware (``import jax as j`` aliases and
     ``from jax import device_get as dg`` renames tracked; a
     same-named local def shadows — the FT003 lesson);
   * a zero-arg ``.block_until_ready()`` attribute call (the method
     exists only on jax arrays);
   * ``np.asarray(E)`` / ``np.array(E)`` where ``np`` provably
     resolves to numpy through the module's imports AND ``E`` is a
     provable device value: an attribute chain ending
     ``.device_out`` (the repo's device-handle idiom), or a local
     assigned EXACTLY once in the scope from such a chain
     (reassigned locals have unknown provenance and never count).

2. **The bypass must be provable**: the finding is suppressed when
   the enclosing function touches the ledger API at all — any
   ``sync_begin`` / ``sync_end`` / ``complete`` / ``dispatched`` /
   ``note_h2d`` attribute (a LaunchRecord in hand), or a call
   reaching ``launch`` / ``global_ledger`` through a tracked alias of
   ``fabric_tpu.observe.ledger`` (or their bare from-imports).  A
   scope that touches the ledger anywhere is assumed to be doing its
   own bracketing — over-suppression is the safe direction here.

3. **Test code is exempt** (``tests/``, ``test_*.py``,
   ``conftest.py``) — differentials sync on purpose.

Intended unledgered syncs carry ``# fabtpu: noqa(FT016)`` with a
comment saying why.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    dotted_name,
    register,
    walk_functions,
)

_LEDGER_MODULES = ("fabric_tpu.observe.ledger",)
_LEDGER_PKG = "fabric_tpu.observe"
#: LaunchRecord / module-API attribute touches that prove the scope
#: participates in the ledger protocol
_RECORD_ATTRS = {"sync_begin", "sync_end", "complete", "dispatched",
                 "note_h2d"}
_LEDGER_FNS = {"launch", "global_ledger"}
_NP_CONVERTERS = {"asarray", "array"}
_DEVICE_ATTR = "device_out"


def _bindings(tree: ast.Module):
    """→ (jax aliases, bare jax sync names, numpy aliases, ledger
    module aliases, bare ledger fn names) from the module's imports
    (function-local imports included — this codebase imports lazily).
    A local def named like a bare import SHADOWS it."""
    jax_aliases: set[str] = set()
    jax_bare: dict[str, str] = {}   # local name -> original fn name
    np_aliases: set[str] = set()
    led_aliases: set[str] = set()
    led_bare: set[str] = set()
    local_defs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                if a.name == "jax" or a.name.startswith("jax."):
                    jax_aliases.add(local if a.asname else "jax")
                elif a.name in ("numpy",):
                    np_aliases.add(local if a.asname else "numpy")
                elif a.name in _LEDGER_MODULES and a.asname:
                    led_aliases.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                local = a.asname or a.name
                if mod == "jax" and a.name in ("device_get",
                                               "block_until_ready"):
                    jax_bare[local] = a.name
                elif mod == _LEDGER_PKG and a.name == "ledger":
                    led_aliases.add(local)
                elif mod in _LEDGER_MODULES and a.name in _LEDGER_FNS:
                    led_bare.add(local)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            local_defs.add(node.name)
    jax_bare = {k: v for k, v in jax_bare.items()
                if k not in local_defs}
    return (jax_aliases - local_defs, jax_bare,
            np_aliases - local_defs, led_aliases - local_defs,
            led_bare - local_defs)


def _walk_own(scope: ast.AST):
    """A scope's own nodes; nested defs are their own scopes."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _is_device_chain(node: ast.AST) -> bool:
    """True for an attribute chain ending ``.device_out``."""
    return (isinstance(node, ast.Attribute)
            and node.attr == _DEVICE_ATTR
            and dotted_name(node) is not None)


def _device_locals(scope: ast.AST) -> set:
    """Locals assigned EXACTLY once in the scope, from a
    ``.device_out`` chain."""
    assigns: dict[str, int] = {}
    from_dev: set[str] = set()
    for node in _walk_own(scope):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            name = node.targets[0].id
            assigns[name] = assigns.get(name, 0) + 1
            if _is_device_chain(node.value):
                from_dev.add(name)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            t = node.target
            if isinstance(t, ast.Name):
                assigns[t.id] = assigns.get(t.id, 0) + 1
    return {n for n in from_dev if assigns.get(n) == 1}


def _touches_ledger(scope: ast.AST, led_aliases: set,
                    led_bare: set) -> bool:
    for node in _walk_own(scope):
        if isinstance(node, ast.Attribute):
            if node.attr in _RECORD_ATTRS:
                return True
            if node.attr in _LEDGER_FNS:
                recv = dotted_name(node.value)
                if recv is not None and recv in led_aliases:
                    return True
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if name is not None and "." not in name and \
                    name in led_bare:
                return True
    return False


@register
class UnattributedDeviceSyncRule(Rule):
    id = "FT016"
    name = "unattributed-device-sync"
    severity = "error"
    description = (
        "flags device syncs (block_until_ready / jax.device_get / "
        "np.asarray on a provable device value) in fabric_tpu/ "
        "functions that provably bypass the launch ledger wrapper — "
        "unattributed device time blinds the compile/queue/execute "
        "decomposition"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        rel = ctx.relpath
        base = rel.rsplit("/", 1)[-1]
        if ("tests/" in rel or rel.startswith("tests")
                or base.startswith("test_") or base == "conftest.py"):
            return []
        (jax_aliases, jax_bare, np_aliases, led_aliases,
         led_bare) = _bindings(ctx.tree)
        out: list[Finding] = []
        for fn in walk_functions(ctx.tree):
            if _touches_ledger(fn, led_aliases, led_bare):
                continue
            dev_locals = _device_locals(fn)
            for node in _walk_own(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._sync_message(node, fn.name, jax_aliases,
                                         jax_bare, np_aliases,
                                         dev_locals)
                if msg is not None:
                    out.append(self.finding(
                        ctx, node.lineno, node.col_offset, msg,
                    ))
        return out

    @staticmethod
    def _sync_message(node: ast.Call, fname: str, jax_aliases: set,
                      jax_bare: dict, np_aliases: set,
                      dev_locals: set) -> str | None:
        name = call_name(node)
        fix = ("wrap the dispatch in observe.ledger.launch() and "
               "bracket this sync with the record's "
               "sync_begin()/sync_end(), or carry a "
               "# fabtpu: noqa(FT016) saying why the wall here is "
               "not device time worth attributing")
        # jax.device_get / jax.block_until_ready through an alias,
        # or their bare from-imports
        if name is not None:
            parts = name.split(".")
            if (len(parts) == 2 and parts[0] in jax_aliases
                    and parts[1] in ("device_get",
                                     "block_until_ready")):
                return (
                    f"'{name}' in '{fname}' syncs the device outside "
                    f"any launch-ledger record — this wall is "
                    f"invisible to the compile/queue/execute "
                    f"attribution; {fix}"
                )
            if len(parts) == 1 and parts[0] in jax_bare:
                return (
                    f"'{parts[0]}' ({jax_bare[parts[0]]}) in "
                    f"'{fname}' syncs the device outside any "
                    f"launch-ledger record; {fix}"
                )
        # zero-arg .block_until_ready() — jax arrays only
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
                and not node.args and not node.keywords):
            base = dotted_name(node.func.value) or "<expr>"
            return (
                f"'.block_until_ready()' on '{base}' in '{fname}' "
                f"syncs the device outside any launch-ledger record; "
                f"{fix}"
            )
        # np.asarray / np.array on a provable device value
        if name is not None and node.args:
            parts = name.split(".")
            if (len(parts) == 2 and parts[0] in np_aliases
                    and parts[1] in _NP_CONVERTERS):
                arg = node.args[0]
                is_dev = _is_device_chain(arg) or (
                    isinstance(arg, ast.Name) and arg.id in dev_locals
                )
                if is_dev:
                    what = (dotted_name(arg)
                            if not isinstance(arg, ast.Name)
                            else arg.id)
                    return (
                        f"'{name}({what})' in '{fname}' reads a "
                        f"device value back to host outside any "
                        f"launch-ledger record; {fix}"
                    )
        return None

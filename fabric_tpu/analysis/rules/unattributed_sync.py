"""FT016 unattributed-device-sync: device syncs that bypass the
launch ledger.

The launch ledger (``fabric_tpu/observe/ledger.py``) is only as
honest as its coverage: every device sync inside ``fabric_tpu/`` hot
paths must run inside a :class:`LaunchRecord` bracket
(``sync_begin``/``sync_end``), or the wall it spends is invisible to
the compile/queue/execute/transfer decomposition — BENCH attribution
and the autopilot's ``device_queue_ms`` signal silently under-read
device pressure.  This rule flags sync constructs that PROVABLY
bypass the wrapper.

Mechanics (strictly under-approximating, per the FT003..FT015
contract — a finding is always real), on the shared provenance
engine (:mod:`fabric_tpu.analysis.provenance`):

1. **A sync construct**, one of:

   * ``jax.device_get(...)`` / ``jax.block_until_ready(...)`` —
     import-aware (``ImportMap``: ``import jax as j`` aliases and
     ``from jax import device_get as dg`` renames tracked; a
     same-named local def shadows — the FT003 lesson);
   * a zero-arg ``.block_until_ready()`` attribute call (the method
     exists only on jax arrays);
   * ``np.asarray(E)`` / ``np.array(E)`` where ``np`` provably
     resolves to numpy through the module's imports AND ``E`` is a
     provable device value: an attribute chain ending
     ``.device_out`` (the repo's device-handle idiom), or a
     single-assignment local bound from such a chain (reassigned
     locals have unknown provenance and never count —
     ``SingleAssignScope``).

2. **The bypass must be provable**: the finding is suppressed when
   the enclosing function touches the ledger API at all — any
   ``sync_begin`` / ``sync_end`` / ``complete`` / ``dispatched`` /
   ``note_h2d`` attribute (a LaunchRecord in hand), or a call
   reaching ``launch`` / ``global_ledger`` through a tracked alias of
   ``fabric_tpu.observe.ledger`` (or their bare from-imports).  A
   scope that touches the ledger anywhere is assumed to be doing its
   own bracketing — over-suppression is the safe direction here.

Test code is exempt engine-wide — differentials sync on purpose.
Intended unledgered syncs carry ``# fabtpu: noqa(FT016)`` with a
comment saying why.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    dotted_name,
    register,
)
from fabric_tpu.analysis.provenance import module_index, walk_scope

_LEDGER_MODULE = "fabric_tpu.observe.ledger"
#: LaunchRecord / module-API attribute touches that prove the scope
#: participates in the ledger protocol
_RECORD_ATTRS = {"sync_begin", "sync_end", "complete", "dispatched",
                 "note_h2d"}
_LEDGER_FNS = {"launch", "global_ledger"}
_LEDGER_BARE = {f"{_LEDGER_MODULE}.{fn}" for fn in _LEDGER_FNS}
_SYNC_FNS = {"device_get", "block_until_ready"}
_NP_CONVERTERS = {"asarray", "array"}
_DEVICE_ATTR = "device_out"


def _is_device_chain(node: ast.AST) -> bool:
    """True for an attribute chain ending ``.device_out``."""
    return (isinstance(node, ast.Attribute)
            and node.attr == _DEVICE_ATTR
            and dotted_name(node) is not None)


def _touches_ledger(scope: ast.AST, imports) -> bool:
    for node in walk_scope(scope):
        if isinstance(node, ast.Attribute):
            if node.attr in _RECORD_ATTRS:
                return True
            if node.attr in _LEDGER_FNS:
                if imports.resolve_node(node.value) == _LEDGER_MODULE:
                    return True
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if (name is not None and "." not in name
                    and imports.resolve(name) in _LEDGER_BARE):
                return True
    return False


@register
class UnattributedDeviceSyncRule(Rule):
    id = "FT016"
    name = "unattributed-device-sync"
    severity = "error"
    description = (
        "flags device syncs (block_until_ready / jax.device_get / "
        "np.asarray on a provable device value) in fabric_tpu/ "
        "functions that provably bypass the launch ledger wrapper — "
        "unattributed device time blinds the compile/queue/execute "
        "decomposition"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        idx = module_index(ctx)
        imports = idx.imports
        out: list[Finding] = []
        for fn in idx.functions:
            if _touches_ledger(fn, imports):
                continue
            dev_locals = idx.scope(fn).names_where(_is_device_chain)
            for node in walk_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._sync_message(node, fn.name, imports,
                                         dev_locals)
                if msg is not None:
                    out.append(self.finding(
                        ctx, node.lineno, node.col_offset, msg,
                    ))
        return out

    @staticmethod
    def _sync_message(node: ast.Call, fname: str, imports,
                      dev_locals: set) -> str | None:
        name = call_name(node)
        fix = ("wrap the dispatch in observe.ledger.launch() and "
               "bracket this sync with the record's "
               "sync_begin()/sync_end(), or carry a "
               "# fabtpu: noqa(FT016) saying why the wall here is "
               "not device time worth attributing")
        # jax.device_get / jax.block_until_ready through an alias,
        # or their bare from-imports
        if name is not None:
            canon = imports.resolve_dotted(name)
            if (canon is not None
                    and canon.split(".")[0] == "jax"
                    and canon.split(".")[-1] in _SYNC_FNS):
                if "." in name:
                    return (
                        f"'{name}' in '{fname}' syncs the device "
                        f"outside any launch-ledger record — this "
                        f"wall is invisible to the compile/queue/"
                        f"execute attribution; {fix}"
                    )
                return (
                    f"'{name}' ({canon.split('.')[-1]}) in "
                    f"'{fname}' syncs the device outside any "
                    f"launch-ledger record; {fix}"
                )
        # zero-arg .block_until_ready() — jax arrays only
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready"
                and not node.args and not node.keywords):
            base = dotted_name(node.func.value) or "<expr>"
            return (
                f"'.block_until_ready()' on '{base}' in '{fname}' "
                f"syncs the device outside any launch-ledger record; "
                f"{fix}"
            )
        # np.asarray / np.array on a provable device value
        if name is not None and node.args:
            parts = name.split(".")
            if (len(parts) == 2
                    and imports.resolve(parts[0]) == "numpy"
                    and parts[1] in _NP_CONVERTERS):
                arg = node.args[0]
                is_dev = _is_device_chain(arg) or (
                    isinstance(arg, ast.Name) and arg.id in dev_locals
                )
                if is_dev:
                    what = (dotted_name(arg)
                            if not isinstance(arg, ast.Name)
                            else arg.id)
                    return (
                        f"'{name}({what})' in '{fname}' reads a "
                        f"device value back to host outside any "
                        f"launch-ledger record; {fix}"
                    )
        return None

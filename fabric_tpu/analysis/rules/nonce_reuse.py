"""FT014 nonce-reuse-hazard: non-deterministic k reaching a sign call.

An ECDSA nonce k that repeats (or is even biased) across two
signatures leaks the private key outright — the Sony PS3 / Android
SecureRandom class of break.  This repo's signing contract is
RFC 6979 (``crypto/ec_ref.rfc6979_candidates``): k is a deterministic
function of (d, digest), derived INSIDE ``sign_digest`` when the
caller passes no nonce, and the device batch-sign lane
(``ops/p256sign``) inherits the same derivation.  A call site that
passes its own ``k`` from a randomness source steps outside that
contract: the caller now owns uniqueness across every signature the
key will ever make, silently, with no replay story — exactly the
hazard the deterministic default exists to remove.  (Explicit k is
legitimate ONLY for pinned test vectors, and test code is exempt
below.)

Mechanics (strictly under-approximating, per the FT003..FT013
contract — a finding is always real):

1. **Sign call sites** — calls whose callee name (attribute or bare)
   is ``sign_digest`` or ``sign`` AND that pass a nonce argument: the
   ``k=`` keyword, or the second positional argument of
   ``sign_digest``.  (Receivers are not resolved — ANY sign-family
   call passing a random k is a hazard worth a look; the randomness
   requirement below is what keeps findings real.)
2. **Randomness provenance, import-aware** (the FT003 lesson — a
   same-named local helper never matches):

   * module-attr calls whose root is an alias of ``secrets``
     (``randbelow``/``randbits``/``token_bytes``), ``random``
     (``randrange``/``randint``/``getrandbits``/``random``), or
     ``os`` (``urandom``), with ``import m as a`` tracked;
   * bare calls whose name was from-imported from those modules
     (renames tracked);
   * ``SystemRandom`` method chains: ``SystemRandom().randrange(n)``
     with the ctor resolved the same way.

   The nonce expression is random if it IS such a call, or reaches
   one through ``int(...)`` / ``int.from_bytes(...)`` wrappers,
   unary/binary arithmetic (the ``% n`` / ``+ 1`` range-fitting
   idioms), or ONE same-scope single-assignment local.  Anything
   else — constants, loop counters, function parameters — stays
   silent: those may still be wrong, but the rule cannot prove it.
3. **Test code is exempt** (``tests/``, ``test_*.py``,
   ``conftest.py``) — pinned RFC vectors and edge-scalar
   differentials pass explicit k on purpose.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    register,
    walk_functions,
)

_SIGN_NAMES = {"sign_digest", "sign"}

#: per-module randomness attributes (module alias → flagged attrs)
_MOD_ATTRS = {
    "secrets": {"randbelow", "randbits", "token_bytes"},
    "random": {"randrange", "randint", "getrandbits", "random"},
    "os": {"urandom"},
}


def _bindings(tree: ast.Module):
    """Import map: ({local alias → canonical module}, {bare name →
    canonical module.attr}, {SystemRandom ctor names})."""
    mod_alias: dict[str, str] = {}
    bare: dict[str, str] = {}
    sysrand: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in _MOD_ATTRS:
                    mod_alias[a.asname or a.name] = a.name
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod not in _MOD_ATTRS and mod != "random":
                continue
            for a in node.names:
                name = a.asname or a.name
                if mod in _MOD_ATTRS and a.name in _MOD_ATTRS[mod]:
                    bare[name] = f"{mod}.{a.name}"
                if mod == "random" and a.name == "SystemRandom":
                    sysrand.add(name)
    return mod_alias, bare, sysrand


class _Scope:
    """One function scope's single-assignment locals.  EVERY other
    binding form — tuple/starred unpacking, aug/ann assignment, for
    targets, comprehensions, walrus, ``with ... as`` — poisons the
    name: its value is then unprovable and the rule stays silent (the
    under-approximation contract; a k rebound by ``k, tag = ...``
    after a random seed must NOT count as the random value)."""

    def __init__(self, fn: ast.AST):
        counts: dict[str, int] = {}
        values: dict[str, ast.expr] = {}

        def poison(target):
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name):
                    counts[sub.id] = counts.get(sub.id, 0) + 99

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    t = node.targets[0]
                    counts[t.id] = counts.get(t.id, 0) + 1
                    values[t.id] = node.value
                else:
                    for t in node.targets:
                        poison(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                                   ast.For, ast.AsyncFor,
                                   ast.comprehension, ast.NamedExpr)):
                poison(node.target)
            elif isinstance(node, ast.withitem):
                if node.optional_vars is not None:
                    poison(node.optional_vars)
        self.single: dict[str, ast.expr] = {
            n: v for n, v in values.items() if counts.get(n) == 1
        }


@register
class NonceReuseHazardRule(Rule):
    id = "FT014"
    name = "nonce-reuse-hazard"
    severity = "error"
    description = (
        "sign/sign_digest call passing a k nonce derived from a "
        "randomness source — nonces must be RFC 6979 deterministic "
        "(omit k) or provably single-use; a repeated k leaks the key"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        rel = ctx.relpath.replace("\\", "/")
        base = rel.rsplit("/", 1)[-1]
        if ("tests/" in rel or rel.startswith("tests")
                or base.startswith("test_") or base == "conftest.py"):
            return []
        mod_alias, bare, sysrand = _bindings(ctx.tree)
        if not (mod_alias or bare or sysrand):
            return []  # no randomness source in scope at all
        out: list[Finding] = []
        for fn in walk_functions(ctx.tree):
            scope = _Scope(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = (node.func.attr
                          if isinstance(node.func, ast.Attribute)
                          else node.func.id
                          if isinstance(node.func, ast.Name) else None)
                if callee not in _SIGN_NAMES:
                    continue
                k_arg = None
                for kw in node.keywords:
                    if kw.arg == "k":
                        k_arg = kw.value
                if (k_arg is None and callee == "sign_digest"
                        and len(node.args) >= 2):
                    k_arg = node.args[1]
                if k_arg is None:
                    continue
                src = self._random_source(
                    k_arg, scope, mod_alias, bare, sysrand, depth=0
                )
                if src is None:
                    continue
                if ctx.suppressed(self, node.lineno):
                    continue
                out.append(self.finding(
                    ctx, node.lineno, node.col_offset,
                    f"{callee}() receives a k nonce derived from "
                    f"{src} — a random per-call nonce has no "
                    f"uniqueness or replay guarantee (one repeat "
                    f"leaks the private key); omit k for the "
                    f"RFC 6979 deterministic derivation",
                ))
        out.sort(key=lambda f: (f.line, f.col))
        return out

    # -- provenance --------------------------------------------------------

    def _random_source(self, node, scope, mod_alias, bare, sysrand,
                       depth: int):
        """The randomness source name if ``node`` provably derives
        from one, else None."""
        if depth > 6:
            return None
        rec = lambda n: self._random_source(
            n, scope, mod_alias, bare, sysrand, depth + 1
        )
        if isinstance(node, ast.Call):
            f = node.func
            # secrets.randbelow(...) / rnd.urandom(...) module attrs
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)):
                mod = mod_alias.get(f.value.id)
                if mod is not None and f.attr in _MOD_ATTRS[mod]:
                    return f"{mod}.{f.attr}"
            # SystemRandom().randrange(...)
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Call)):
                ctor = f.value.func
                if ((isinstance(ctor, ast.Name) and ctor.id in sysrand)
                        or (isinstance(ctor, ast.Attribute)
                            and isinstance(ctor.value, ast.Name)
                            and mod_alias.get(ctor.value.id) == "random"
                            and ctor.attr == "SystemRandom")):
                    return f"random.SystemRandom().{f.attr}"
            # from-imported bare names (renames tracked)
            if isinstance(f, ast.Name) and f.id in bare:
                return bare[f.id]
            # int(x) / int.from_bytes(x, ...) wrappers
            if ((isinstance(f, ast.Name) and f.id == "int")
                    or (isinstance(f, ast.Attribute)
                        and f.attr == "from_bytes"
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "int")):
                if node.args:
                    return rec(node.args[0])
            return None
        if isinstance(node, ast.BinOp):  # k0 % n, k0 + 1, ...
            return rec(node.left) or rec(node.right)
        if isinstance(node, ast.UnaryOp):
            return rec(node.operand)
        if isinstance(node, ast.Name):  # one single-assignment local
            val = scope.single.get(node.id)
            if val is not None:
                return rec(val)
        return None

"""FT014 nonce-reuse-hazard: non-deterministic k reaching a sign call.

An ECDSA nonce k that repeats (or is even biased) across two
signatures leaks the private key outright — the Sony PS3 / Android
SecureRandom class of break.  This repo's signing contract is
RFC 6979 (``crypto/ec_ref.rfc6979_candidates``): k is a deterministic
function of (d, digest), derived INSIDE ``sign_digest`` when the
caller passes no nonce, and the device batch-sign lane
(``ops/p256sign``) inherits the same derivation.  A call site that
passes its own ``k`` from a randomness source steps outside that
contract: the caller now owns uniqueness across every signature the
key will ever make, silently, with no replay story — exactly the
hazard the deterministic default exists to remove.  (Explicit k is
legitimate ONLY for pinned test vectors, and test code is exempt
engine-wide.)

Mechanics (strictly under-approximating, per the FT003..FT013
contract — a finding is always real), on the shared provenance
engine (:mod:`fabric_tpu.analysis.provenance`):

1. **Sign call sites** — calls whose callee name (attribute or bare)
   is ``sign_digest`` or ``sign`` AND that pass a nonce argument: the
   ``k=`` keyword, or the second positional argument of
   ``sign_digest``.  (Receivers are not resolved — ANY sign-family
   call passing a random k is a hazard worth a look; the randomness
   requirement below is what keeps findings real.)
2. **Randomness provenance, import-aware** (``ImportMap`` — aliases
   and from-import renames tracked, a same-named local helper never
   matches): ``secrets.randbelow``/``randbits``/``token_bytes``,
   ``random.randrange``/``randint``/``getrandbits``/``random``,
   ``os.urandom``, and ``SystemRandom()`` method chains.  The nonce
   expression is random if it IS such a call, or reaches one through
   ``int(...)`` / ``int.from_bytes(...)`` wrappers, unary/binary
   arithmetic (the ``% n`` / ``+ 1`` range-fitting idioms), or ONE
   same-scope single-assignment local (``SingleAssignScope`` — every
   other binding form poisons).  Anything else — constants, loop
   counters, function parameters — stays silent: those may still be
   wrong, but the rule cannot prove it.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import Finding, ModuleCtx, Rule, register
from fabric_tpu.analysis.provenance import module_index, walk_scope

_SIGN_NAMES = {"sign_digest", "sign"}

#: canonical dotted names of the flagged randomness sources
_RANDOM_SOURCES = {
    "secrets.randbelow", "secrets.randbits", "secrets.token_bytes",
    "random.randrange", "random.randint", "random.getrandbits",
    "random.random",
    "os.urandom",
}
_RANDOM_ROOTS = {"secrets", "random", "os"}
_SYSRAND = "random.SystemRandom"


@register
class NonceReuseHazardRule(Rule):
    id = "FT014"
    name = "nonce-reuse-hazard"
    severity = "error"
    description = (
        "sign/sign_digest call passing a k nonce derived from a "
        "randomness source — nonces must be RFC 6979 deterministic "
        "(omit k) or provably single-use; a repeated k leaks the key"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        idx = module_index(ctx)
        imports = idx.imports
        if not imports.any_binding(
            lambda c: c.split(".")[0] in _RANDOM_ROOTS
        ):
            return []  # no randomness source in scope at all
        out: list[Finding] = []
        for fn in idx.functions:
            scope = idx.scope(fn)
            for node in walk_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = (node.func.attr
                          if isinstance(node.func, ast.Attribute)
                          else node.func.id
                          if isinstance(node.func, ast.Name) else None)
                if callee not in _SIGN_NAMES:
                    continue
                k_arg = None
                for kw in node.keywords:
                    if kw.arg == "k":
                        k_arg = kw.value
                if (k_arg is None and callee == "sign_digest"
                        and len(node.args) >= 2):
                    k_arg = node.args[1]
                if k_arg is None:
                    continue
                src = self._random_source(k_arg, scope, imports, depth=0)
                if src is None:
                    continue
                if ctx.suppressed(self, node.lineno):
                    continue
                out.append(self.finding(
                    ctx, node.lineno, node.col_offset,
                    f"{callee}() receives a k nonce derived from "
                    f"{src} — a random per-call nonce has no "
                    f"uniqueness or replay guarantee (one repeat "
                    f"leaks the private key); omit k for the "
                    f"RFC 6979 deterministic derivation",
                ))
        out.sort(key=lambda f: (f.line, f.col))
        return out

    # -- provenance --------------------------------------------------------

    def _random_source(self, node, scope, imports, depth: int):
        """The randomness source name if ``node`` provably derives
        from one, else None."""
        if depth > 6:
            return None
        rec = lambda n: self._random_source(n, scope, imports, depth + 1)
        if isinstance(node, ast.Call):
            f = node.func
            # secrets.randbelow(...) / rnd.urandom(...) module attrs
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)):
                mod = imports.resolve(f.value.id)
                if mod is not None and f"{mod}.{f.attr}" in _RANDOM_SOURCES:
                    return f"{mod}.{f.attr}"
            # SystemRandom().randrange(...)
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Call)
                    and imports.resolve_node(f.value.func) == _SYSRAND):
                return f"random.SystemRandom().{f.attr}"
            # from-imported bare names (renames tracked)
            if isinstance(f, ast.Name):
                canon = imports.resolve(f.id)
                if canon in _RANDOM_SOURCES:
                    return canon
            # int(x) / int.from_bytes(x, ...) wrappers
            if ((isinstance(f, ast.Name) and f.id == "int")
                    or (isinstance(f, ast.Attribute)
                        and f.attr == "from_bytes"
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "int")):
                if node.args:
                    return rec(node.args[0])
            return None
        if isinstance(node, ast.BinOp):  # k0 % n, k0 + 1, ...
            return rec(node.left) or rec(node.right)
        if isinstance(node, ast.UnaryOp):
            return rec(node.operand)
        if isinstance(node, ast.Name):  # one single-assignment local
            val = scope.value_of(node.id)
            if val is not None:
                return rec(val)
        return None

"""FT011 device-buffer-lifetime: packed uploads pinned past their fetch.

A packed device upload — ``jax.device_put``, ``parallel.mesh.
shard_batch``, or an ``ops.p256v3`` packed launch frame
(``pack_cols`` / ``pack_cols_limbs`` / ``prepare_cols_packed``) — is
multi-MB per block at production batch sizes.  Binding it to a local
and leaving that local alive after the consuming fetch/sync pins the
buffer (device memory for sharded uploads; the host-side H2D source
either way) until scope exit, which at 3072-lane frames means a whole
extra frame resident per in-flight block — exactly the ROADMAP's
"device-memory lifetime (packed uploads outliving their fetch)"
lever.  The fix is a ``del``, a narrower scope, or handing the buffer
off instead of keeping it.

Mechanics (strictly under-approximating, per the FT003..FT010
contract — a finding is always real):

1. **Upload sites** — calls resolved IMPORT-AWARE (the FT003 lesson: a
   same-named local helper never matches): ``<jax alias>.device_put``
   or a bare ``device_put`` from-imported from jax;
   ``shard_batch`` bare-imported from (or attribute-called on an alias
   of) ``fabric_tpu.parallel.mesh``; ``pack_cols`` /
   ``pack_cols_limbs`` / ``prepare_cols_packed`` likewise from
   ``fabric_tpu.ops.p256v3``.
2. **Lifetime test** — a site is flagged only when ALL of:

   * the result binds a plain local name assigned exactly ONCE in the
     scope, outside any loop (loop bodies reorder textually — skipped
     outright), never ``del``-ed;
   * every Load of the name is a plain consumption (an argument to a
     call, an expression operand).  A Load inside a ``return`` /
     ``yield``, stored onto an attribute / subscript / container
     literal, or aliased to another name ESCAPES — the lifetime is
     someone else's by design, so the site is skipped;
   * a sync-family call — an attribute call named ``fetch`` /
     ``block_until_ready``, or ``jax.device_get`` — appears in the
     scope lexically AFTER the name's last Load.  From that point the
     buffer is provably no longer needed, yet the local pins it until
     scope exit regardless of which path the sync ran on.

3. **Test code is exempt** (``tests/``, ``test_*.py``,
   ``conftest.py``) — fixtures hold buffers on purpose to compare
   against.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    register,
    walk_functions,
)

#: bare names by source module (from-imports, renames tracked)
_UPLOADS_BY_MODULE = {
    "jax": {"device_put"},
    "fabric_tpu.parallel.mesh": {"shard_batch"},
    "fabric_tpu.ops.p256v3": {
        "pack_cols", "pack_cols_limbs", "prepare_cols_packed"
    },
}
#: attribute names valid on an alias of the keyed module
_UPLOAD_ATTRS = {
    "jax": {"device_put"},
    "fabric_tpu.parallel.mesh": {"shard_batch"},
    "fabric_tpu.ops.p256v3": {
        "pack_cols", "pack_cols_limbs", "prepare_cols_packed"
    },
}
_SYNC_ATTRS = {"fetch", "block_until_ready"}


def _bindings(tree: ast.Module) -> tuple[dict, set, set]:
    """(module_alias → canonical module, bare upload names,
    bare/aliased device_get names) over the whole module — imports are
    commonly function-local in this tree, so the walk is global."""
    aliases: dict[str, str] = {}
    bare: set[str] = set()
    get_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                for mod in _UPLOAD_ATTRS:
                    if a.name == mod or (
                        mod == "jax" and a.name.startswith("jax.")
                    ):
                        aliases[a.asname or a.name.split(".")[0]] = (
                            "jax" if a.name.startswith("jax") else mod
                        )
                # plain `import fabric_tpu.ops.p256v3 as v3` etc.
                if a.name in _UPLOAD_ATTRS:
                    aliases[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for canon, names in _UPLOADS_BY_MODULE.items():
                # suffix match covers relative/abbreviated forms
                # (`from fabric_tpu.ops import p256v3`, `from ..ops
                # import p256v3` import the MODULE — handled via
                # aliases below; `from ...p256v3 import pack_cols`
                # binds the bare name)
                if mod == canon or canon.endswith("." + mod) or (
                    mod and canon.split(".")[-1] == mod.split(".")[-1]
                ):
                    for a in node.names:
                        if a.name in names:
                            bare.add(a.asname or a.name)
            if mod.split(".")[0] == "jax":
                for a in node.names:
                    if a.name == "device_get":
                        get_names.add(a.asname or a.name)
            # `from fabric_tpu.ops import p256v3 [as v3]` — module
            # object bound as a name: record as an alias
            for a in node.names:
                for canon in _UPLOAD_ATTRS:
                    if canon == (f"{mod}.{a.name}" if mod else a.name) \
                            or canon.endswith("." + a.name) and (
                                not mod or canon.startswith(mod)):
                        aliases[a.asname or a.name] = canon
    return aliases, bare, get_names


def _is_upload_call(node: ast.AST, aliases: dict, bare: set) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    if name is None:
        return False
    parts = name.split(".")
    if len(parts) == 1:
        return parts[0] in bare
    if len(parts) == 2 and parts[0] in aliases:
        return parts[1] in _UPLOAD_ATTRS[aliases[parts[0]]]
    return False


def _is_sync_call(node: ast.Call, aliases: dict, get_names: set) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS:
        return True
    name = call_name(node)
    if name is None:
        return False
    parts = name.split(".")
    if len(parts) == 1:
        return parts[0] in get_names
    return (len(parts) == 2 and aliases.get(parts[0]) == "jax"
            and parts[1] == "device_get")


def _walk_own(scope: ast.AST, *, skip_loops: bool = False):
    """A scope's OWN nodes; nested defs are their own scopes.  With
    ``skip_loops``, loop bodies are not descended into (textual order
    is meaningless across iterations)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if skip_loops and isinstance(node, (ast.For, ast.AsyncFor,
                                            ast.While)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _load_profile(scope: ast.AST, name: str) -> tuple[int, bool, int]:
    """(last_load_line, escaped, n_stores) over the scope's subtree
    (closures included — a closure keeping the buffer is an escape)."""
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(scope):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    last = -1
    escaped = False
    stores = 0
    for node in ast.walk(scope):
        if not (isinstance(node, ast.Name) and node.id == name):
            continue
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            stores += 1
            continue
        last = max(last, node.lineno)
        # walk up: a Load under Return/Yield escapes; rhs of an
        # aliasing Assign / element of a container literal / value of
        # an attribute-or-subscript store escapes; a call ARGUMENT is
        # plain consumption
        cur: ast.AST = node
        while True:
            parent = parents.get(id(cur))
            if parent is None:
                break
            if isinstance(parent, (ast.Return, ast.Yield,
                                   ast.YieldFrom)):
                escaped = True
                break
            if isinstance(parent, (ast.List, ast.Tuple, ast.Set,
                                   ast.Dict)):
                escaped = True
                break
            if isinstance(parent, ast.Assign) and cur is parent.value:
                escaped = True  # aliased or stored somewhere durable
                break
            if isinstance(parent, ast.Call) and cur is not parent.func:
                # an argument to a METHOD call (frames.append(v),
                # scheduler.submit(v)) may be retained by the receiver
                # — escape; a plain-name call (kern(v), fn(v)) is the
                # dispatch-consumption shape
                if isinstance(parent.func, ast.Attribute):
                    escaped = True
                break
            if isinstance(parent, ast.stmt):
                break
            cur = parent
    return last, escaped, stores


@register
class DeviceBufferLifetimeRule(Rule):
    id = "FT011"
    name = "device-buffer-lifetime"
    severity = "warning"
    description = (
        "flags packed device uploads (device_put / shard_batch / "
        "pack_cols-family frames) bound to locals that stay alive "
        "past the consuming fetch/sync — the local pins a multi-MB "
        "buffer until scope exit; del it or narrow the scope"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        rel = ctx.relpath
        base = rel.rsplit("/", 1)[-1]
        if ("tests/" in rel or rel.startswith("tests")
                or base.startswith("test_") or base == "conftest.py"):
            return []
        aliases, bare, get_names = _bindings(ctx.tree)
        if not aliases and not bare:
            return []
        out: list[Finding] = []
        scopes = [ctx.tree] + list(walk_functions(ctx.tree))
        for scope in scopes:
            # locally-defined names shadow the imports (the FT003
            # lesson: a nested `def pack_cols` must never match)
            shadowed = {
                n.name for n in ast.walk(scope)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not scope
            }
            my_bare = bare - shadowed
            # sync sites anywhere in the scope's own statements
            sync_lines = [
                n.lineno for n in _walk_own(scope)
                if isinstance(n, ast.Call)
                and _is_sync_call(n, aliases, get_names)
            ]
            if not sync_lines:
                continue
            last_sync = max(sync_lines)
            for node in _walk_own(scope, skip_loops=True):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and _is_upload_call(node.value, aliases,
                                            my_bare)):
                    continue
                tgt = node.targets[0].id
                last_load, escaped, stores = _load_profile(scope, tgt)
                # stores == 1: exactly the binding itself — a rebind
                # or del elsewhere manages the lifetime already
                if escaped or stores != 1 or last_load < 0:
                    continue
                if last_sync > last_load:
                    out.append(self.finding(
                        ctx, node.lineno, node.col_offset,
                        f"'{tgt}' binds a packed device upload whose "
                        f"last use is on line {last_load}, but the "
                        f"scope syncs results afterwards (line "
                        f"{last_sync}) and '{tgt}' stays alive to "
                        "scope exit — at production batch sizes that "
                        "pins a multi-MB frame (and its device copy) "
                        "a whole extra block; del it after its "
                        "dispatch or narrow its scope",
                    ))
        return out

"""FT009 unbounded-blocking-wait: thread-blocking waits with no
timeout outside test code.

The commit path is a lattice of worker threads (prefetch, committer,
host-pool, feeder) handing work through futures, queues and events.  A
``Future.result()`` / ``Queue.get()`` / ``Event.wait()`` /
``Thread.join()`` with NO timeout turns any wedged producer (a hung
fsync, a dead device runtime, a stuck RPC) into a silently frozen
consumer — the exact failure the chaos harness (fabric_tpu.faults)
injects and the degraded-mode machinery routes around.  The bounded
discipline: pass ``timeout=`` and handle it (retry loop with progress
logging, or abort), or mark an INTENTIONALLY unbounded wait with
``# fabtpu: noqa(FT009)`` and a justification.

Mechanics (import-aware per the FT003/FT007/FT008 pattern, strictly
under-approximating so a finding is always real):

1. **Tracked objects** — resolved THROUGH the module's imports
   (aliases and from-import renames included):

   * ``threading.Event()``            → event   (``.wait()``)
   * ``threading.Thread(...)``        → thread  (``.join()``)
   * ``queue.Queue/LifoQueue/PriorityQueue/SimpleQueue()``
                                      → queue   (``.get()``)
   * ``concurrent.futures.Future()``  → future  (``.result()``)
   * ``asyncio.run_coroutine_threadsafe(...)`` → future
   * ``ThreadPoolExecutor/ProcessPoolExecutor(...)`` → executor, whose
     ``.submit(...)`` results are futures (chained
     ``ex.submit(...).result()`` included)

   Receivers are tracked through same-scope local assignment
   (element-wise tuple assigns included — the ``fut, self._f =
   self._f, None`` pop idiom), through ``self.<attr>`` assigned
   anywhere in the SAME class, and through direct chained calls.
   Anything else (tuple unpacks of unknown tuples, containers,
   parameters) is invisible by design — under-approximation keeps
   false positives at zero.

2. **Bounded test** — ``.get()`` is bounded with a ``timeout=`` kw or
   a second positional (``get(True, 5)``); the others with any
   positional or a ``timeout=`` kw.  ``get_nowait`` etc. never match.
   ``await``-ed calls never match (asyncio waits don't block a
   thread; cancellation is the loop's concern).

3. **Test code is exempt** — paths under ``tests/``, ``test_*.py``
   and ``conftest.py``: an unbounded wait in a test hangs CI, which
   has its own timeout, and test clarity wins.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    register,
)

_MODULES = ("threading", "queue", "asyncio", "concurrent.futures",
            "concurrent")

_CTOR_KINDS = {
    ("threading", "Event"): "event",
    ("threading", "Thread"): "thread",
    ("threading", "Timer"): "thread",
    ("queue", "Queue"): "queue",
    ("queue", "LifoQueue"): "queue",
    ("queue", "PriorityQueue"): "queue",
    ("queue", "SimpleQueue"): "queue",
    ("concurrent.futures", "ThreadPoolExecutor"): "executor",
    ("concurrent.futures", "ProcessPoolExecutor"): "executor",
    ("concurrent.futures", "Future"): "future",
    ("asyncio", "run_coroutine_threadsafe"): "future",
}

#: method → the receiver kind it blocks on
_WAITS = {"wait": "event", "join": "thread", "get": "queue",
          "result": "future"}

_ADVICE = {
    "event": "Event.wait() with no timeout blocks this thread forever "
             "if the setter dies",
    "thread": "Thread.join() with no timeout blocks forever if the "
              "thread wedges",
    "queue": "Queue.get() with no timeout blocks forever if the "
             "producer dies",
    "future": "Future.result() with no timeout blocks forever if the "
              "producer wedges",
}


def _bindings(tree: ast.Module):
    """(dotted-prefix → canonical module, bare name → (module, orig))
    for the modules of interest, from every import in the module."""
    prefixes: dict[str, str] = {}
    bare: dict[str, tuple] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                root = a.name.split(".")[0]
                if root not in ("threading", "queue", "asyncio",
                                "concurrent"):
                    continue
                if a.asname:
                    prefixes[a.asname] = a.name
                else:
                    # `import concurrent.futures` binds "concurrent";
                    # the dotted CALL path is the full module path
                    prefixes[a.name] = a.name
                    prefixes[root] = root
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "concurrent" :
                for a in node.names:
                    if a.name == "futures":
                        prefixes[a.asname or "futures"] = (
                            "concurrent.futures"
                        )
                continue
            if mod not in ("threading", "queue", "asyncio",
                           "concurrent.futures"):
                continue
            for a in node.names:
                bare[a.asname or a.name] = (mod, a.name)
    return prefixes, bare


def _classify_call(call: ast.Call, prefixes, bare) -> str | None:
    """Call → tracked kind, resolved through the imports."""
    name = call_name(call)
    if name is None:
        return None
    if "." in name:
        mod_path, _, attr = name.rpartition(".")
        module = prefixes.get(mod_path)
        if module == "concurrent":
            module = None  # bare `concurrent.X` is not a tracked attr
        if module is None:
            return None
        return _CTOR_KINDS.get((module, attr))
    return _CTOR_KINDS.get(bare.get(name, ("", "")))


def _class_attrs(cls: ast.ClassDef, prefixes, bare) -> dict[str, str]:
    """self.<attr> kinds assigned anywhere in the class (ctor calls,
    then submit-derived futures off executor attrs)."""
    attrs: dict[str, str] = {}

    def targets(node):
        for t in node.targets if isinstance(node, ast.Assign) else ():
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                yield t.attr

    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            kind = _classify_call(node.value, prefixes, bare)
            if kind:
                for attr in targets(node):
                    attrs[attr] = kind
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            f = node.value.func
            if (isinstance(f, ast.Attribute) and f.attr == "submit"
                    and isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id == "self"
                    and attrs.get(f.value.attr) == "executor"):
                for attr in targets(node):
                    attrs[attr] = "future"
    return attrs


def _walk_own(scope: ast.AST):
    """A scope's OWN nodes (nested defs/lambdas are their own scopes)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _bounded(call: ast.Call, meth: str) -> bool:
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    if meth == "get":
        # get(block=False) / get(False) never blocks — it raises
        # queue.Empty immediately, so there is no wait to bound
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                return True
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value is False:
            return True
    need = 2 if meth == "get" else 1  # get(block, timeout)
    return len(call.args) >= need


@register
class BlockingWaitRule(Rule):
    id = "FT009"
    name = "unbounded-blocking-wait"
    severity = "error"
    description = (
        "flags Future.result()/Queue.get()/Event.wait()/Thread.join() "
        "without a timeout outside test code — a wedged producer "
        "freezes the waiting thread forever; pass timeout= and handle "
        "it, or noqa an intentionally unbounded wait"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        rel = ctx.relpath
        base = rel.rsplit("/", 1)[-1]
        if ("tests/" in rel or rel.startswith("tests")
                or base.startswith("test_") or base == "conftest.py"):
            return []
        prefixes, bare = _bindings(ctx.tree)
        if not (prefixes or bare):
            return []
        # awaited calls never block a thread — mark and skip them
        awaited = {
            id(node.value) for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Await)
        }
        out: list[Finding] = []
        for scope, cls_attrs in self._scopes(ctx.tree, prefixes, bare):
            self._check_scope(ctx, scope, cls_attrs, prefixes, bare,
                              awaited, out)
        return out

    def _scopes(self, tree, prefixes, bare):
        """(scope, enclosing-class attr kinds) for the module and every
        function, computing each class's attr map once."""
        out = [(tree, {})]

        def rec(node, cls_attrs):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    rec(child, _class_attrs(child, prefixes, bare))
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    out.append((child, cls_attrs))
                    rec(child, cls_attrs)
                else:
                    rec(child, cls_attrs)

        rec(tree, {})
        return out

    def _check_scope(self, ctx, scope, cls_attrs, prefixes, bare,
                     awaited, out):
        # pass 1: same-scope local kinds (element-wise tuple assigns
        # included — the `fut, self._f = self._f, None` pop idiom)
        local: dict[str, str] = {}

        def expr_kind(expr) -> str | None:
            if isinstance(expr, ast.Name):
                return local.get(expr.id)
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return cls_attrs.get(expr.attr)
            if isinstance(expr, ast.Call):
                kind = _classify_call(expr, prefixes, bare)
                if kind:
                    return kind
                f = expr.func
                if (isinstance(f, ast.Attribute) and f.attr == "submit"
                        and expr_kind(f.value) == "executor"):
                    return "future"
            return None

        # source order: `f = ex.submit(...)` must see the earlier
        # `ex = ThreadPoolExecutor(...)` (the walk itself is unordered)
        assigns = sorted(
            (n for n in _walk_own(scope) if isinstance(n, ast.Assign)),
            key=lambda n: (n.lineno, n.col_offset),
        )
        for node in assigns:
            if len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                kind = expr_kind(node.value)
                if kind:
                    local[tgt.id] = kind
            elif (isinstance(tgt, ast.Tuple)
                  and isinstance(node.value, ast.Tuple)
                  and len(tgt.elts) == len(node.value.elts)):
                for t, v in zip(tgt.elts, node.value.elts):
                    if isinstance(t, ast.Name):
                        kind = expr_kind(v)
                        if kind:
                            local[t.id] = kind

        # pass 2: the waits
        for node in _walk_own(scope):
            if not isinstance(node, ast.Call) or id(node) in awaited:
                continue
            f = node.func
            if not isinstance(f, ast.Attribute) or f.attr not in _WAITS:
                continue
            want = _WAITS[f.attr]
            if expr_kind(f.value) != want or _bounded(node, f.attr):
                continue
            out.append(self.finding(
                ctx, node.lineno, node.col_offset,
                f"{_ADVICE[want]} — pass timeout= and handle it "
                "(bounded retry loop with progress logging, or abort), "
                "or mark an intentionally unbounded wait with "
                "# fabtpu: noqa(FT009)",
            ))

"""Shared detection of jit-compiled functions (FT001/FT002).

A function is "jitted" when it is

* decorated with ``jax.jit`` / ``jit`` / ``jax.pmap`` / ``pmap`` /
  ``shard_map`` (bare or via ``partial(jax.jit, ...)`` /
  ``jax.jit(...)``-with-kwargs decorator factories), or
* passed as the first positional argument to a ``jax.jit(...)`` /
  ``pmap(...)`` / ``shard_map(...)`` call anywhere in the module
  (``verify = jax.jit(_verify_impl, ...)``).

``static_info`` also extracts ``static_argnums`` / ``static_argnames``
literals so the retrace rule can reason about which parameters are
traced.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from fabric_tpu.analysis.core import call_name, dotted_name

_JIT_NAMES = {
    "jax.jit", "jit", "jax.pmap", "pmap", "shard_map",
    "jax.experimental.shard_map.shard_map", "checkpoint_name",
    "jax.named_call",
}
_WRAPPER_NAMES = {"partial", "functools.partial"}


def _jit_call(node: ast.AST) -> ast.Call | None:
    """The jit/pmap/shard_map Call inside a (possibly partial-wrapped)
    expression, or None."""
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    if name in _JIT_NAMES:
        return node
    if name in _WRAPPER_NAMES and node.args:
        inner = dotted_name(node.args[0])
        if inner in _JIT_NAMES:
            return node
    return None


def _is_jit_decorator(dec: ast.AST) -> ast.Call | None | bool:
    """→ the configuring Call for ``@partial(jax.jit, ...)`` /
    ``@jax.jit(...)``, True for a bare ``@jax.jit``, else False."""
    if dotted_name(dec) in _JIT_NAMES:
        return True
    call = _jit_call(dec)
    return call if call is not None else False


@dataclass
class JittedFn:
    node: ast.FunctionDef
    static_argnums: set[int] = field(default_factory=set)
    static_argnames: set[str] = field(default_factory=set)
    via: str = "decorator"  # or "call"


def _static_info(call: ast.Call, jf: JittedFn) -> None:
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for v in ast.walk(kw.value):
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    jf.static_argnums.add(v.value)
        elif kw.arg == "static_argnames":
            for v in ast.walk(kw.value):
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    jf.static_argnames.add(v.value)


def find_jitted(tree: ast.AST) -> dict[str, JittedFn]:
    """name → JittedFn for every jit-compiled function in the module."""
    defs: dict[str, ast.FunctionDef] = {}
    out: dict[str, JittedFn] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
            for dec in node.decorator_list:
                got = _is_jit_decorator(dec)
                if got is False:
                    continue
                jf = out.setdefault(node.name, JittedFn(node))
                if isinstance(got, ast.Call):
                    _static_info(got, jf)
    # call-form: f = jax.jit(g, ...) with g a module function
    for node in ast.walk(tree):
        call = _jit_call(node)
        if call is None or not call.args:
            continue
        target = call.args[0]
        if call_name(call) in _WRAPPER_NAMES:
            # partial(jax.jit, ...) as a decorator was handled above;
            # partial(jax.jit)(g) is not a pattern worth chasing
            continue
        tname = dotted_name(target)
        if tname in defs:
            jf = out.setdefault(tname, JittedFn(defs[tname], via="call"))
            _static_info(call, jf)
    return out


def local_names(fn: ast.FunctionDef) -> set[str]:
    """Names bound inside ``fn`` (params, assignments, loop/with
    targets, walrus, nested defs) — everything NOT closed over."""
    names: set[str] = set()
    a = fn.args
    for arg in (
        list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
        + ([a.vararg] if a.vararg else []) + ([a.kwarg] if a.kwarg else [])
    ):
        names.add(arg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                names.add(node.name)
        elif isinstance(node, ast.NamedExpr) and isinstance(
                node.target, ast.Name):
            names.add(node.target.id)
    return names

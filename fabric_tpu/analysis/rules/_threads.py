"""Shared concurrency scan for the cross-thread rules (FT017/FT018).

Both rules reason about the same three facts of a class:

* **which self-attrs are locks** — ctor-proven (``self._lock =
  threading.Lock()`` resolved import-aware through the provenance
  engine's :class:`~fabric_tpu.analysis.provenance.ImportMap`) plus
  the FT004 textual convention (an attr whose name contains ``lock``
  or ``mutex``, or ends in ``cond`` — the repo's ``self._cond``
  Condition idiom);
* **which locks a statement holds** — lexical ``with`` tracking, one
  scan per method (:func:`scan_method`), recognizing ``with
  self._lock:``, ``with self._cond:`` and the ``.acquire()`` /
  ``.reader()`` / ``.writer()`` call forms;
* **the intra-class call graph** — ``self.m(...)`` edges with the
  held-set at the call site, so a ``_flush_locked``-style helper
  inherits the caller's lock interprocedurally.

Everything here under-approximates: a lock reached any other way
(global, passed in, attribute chain) is invisible, an unrecognized
``with`` item holds nothing — both directions only make the two
rules QUIETER, never wrong.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from fabric_tpu.analysis.core import dotted_name
from fabric_tpu.analysis.provenance import class_self_attrs, walk_scope

#: canonical dotted names of the threading lock constructors
LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}
#: canonical dotted names of the pool-executor constructors
EXECUTOR_CTORS = {
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
    "concurrent.futures.thread.ThreadPoolExecutor",
    "concurrent.futures.process.ProcessPoolExecutor",
}
#: container-mutating method names — a ``self.X.append(...)`` is a
#: WRITE to X for race purposes
MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert",
    "pop", "popleft", "remove", "discard", "clear", "add",
    "update", "setdefault", "rotate",
}


def _textual_lock_name(name: str) -> bool:
    low = name.lower()
    return "lock" in low or "mutex" in low or low.endswith("cond")


def lock_attr_names(cls: ast.ClassDef, imports) -> set[str]:
    """Self-attr names of ``cls`` that are locks: ctor-proven
    threading primitives plus the textual naming convention."""
    proven = class_self_attrs(
        cls,
        lambda v: (isinstance(v, ast.Call)
                   and imports.resolve_call(v) in LOCK_CTORS),
    )
    textual = {
        a for a in class_self_attrs(cls, lambda v: True)
        if _textual_lock_name(a)
    }
    return proven | textual


def executor_attr_names(cls: ast.ClassDef, imports) -> set[str]:
    """Self-attr names provably bound from a pool executor ctor."""
    return class_self_attrs(
        cls,
        lambda v: (isinstance(v, ast.Call)
                   and imports.resolve_call(v) in EXECUTOR_CTORS),
    )


def self_attr(node: ast.AST) -> str | None:
    """``self.X`` → ``"X"``, else None (deeper chains excluded)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _with_lock_token(item: ast.withitem, lock_names: set[str]) -> str | None:
    """The lock identity a ``with`` item acquires, or None.  A
    ``self.X`` in ``lock_names`` — bare, or through ``.acquire()`` /
    ``.reader()`` / ``.writer()`` — yields ``"self.X"``; any other
    dotted name passes only on the textual convention."""
    node = item.context_expr
    if isinstance(node, ast.Call):
        f = node.func
        if (isinstance(f, ast.Attribute)
                and f.attr in ("acquire", "reader", "writer")):
            node = f.value
        else:
            node = f
    dn = dotted_name(node)
    if dn is None:
        return None
    parts = dn.split(".")
    if parts[0] == "self" and len(parts) == 2:
        if parts[1] in lock_names:
            return dn
        return None
    if _textual_lock_name(parts[-1]):
        return dn
    return None


@dataclass(frozen=True)
class Access:
    """One touch of a ``self.`` attribute inside a method."""

    attr: str
    kind: str            # "read" | "write"
    line: int
    col: int
    held: frozenset      # lock tokens held at the access


@dataclass(frozen=True)
class Call:
    """One intra-class ``self.m(...)`` call edge."""

    callee: str
    held: frozenset
    line: int


def scan_method(fn: ast.AST, lock_names: set[str]):
    """→ ``(accesses, calls)`` of one method body.

    Lexical scan with a ``with``-stack: every ``self.X``
    read/write/mutator-call/subscript-store is recorded with the lock
    tokens held at that point; every ``self.m(...)`` call becomes an
    edge carrying its held-set.  Nested defs/lambdas are skipped (they
    run on their own schedule — a closure handed to a thread is a
    spawn site, not a body extension)."""
    accesses: list[Access] = []
    calls: list[Call] = []

    def visit(node: ast.AST, held: frozenset):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                tok = _with_lock_token(item, lock_names)
                if tok is not None:
                    inner.add(tok)
                else:
                    visit(item.context_expr, held)
                if item.optional_vars is not None:
                    visit(item.optional_vars, held)
            inner_f = frozenset(inner)
            for stmt in node.body:
                visit(stmt, inner_f)
            return
        if isinstance(node, ast.Call):
            f = node.func
            callee = self_attr(f)
            if callee is not None:
                calls.append(Call(callee, held, node.lineno))
            if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
                base = self_attr(f.value)
                if base is not None and base not in lock_names:
                    accesses.append(Access(
                        base, "write", node.lineno, node.col_offset, held,
                    ))
        elif isinstance(node, ast.Attribute):
            a = self_attr(node)
            if a is not None and a not in lock_names:
                kind = ("write"
                        if isinstance(node.ctx, (ast.Store, ast.Del))
                        else "read")
                accesses.append(Access(
                    a, kind, node.lineno, node.col_offset, held,
                ))
        elif isinstance(node, ast.Subscript):
            base = self_attr(node.value)
            if (base is not None and base not in lock_names
                    and isinstance(node.ctx, (ast.Store, ast.Del))):
                accesses.append(Access(
                    base, "write", node.lineno, node.col_offset, held,
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    empty = frozenset()
    for stmt in getattr(fn, "body", []):
        visit(stmt, empty)
    return accesses, calls


def scan_class(cls: ast.ClassDef, methods: dict, imports):
    """Scan every direct method of ``cls`` once.  → ``(lock_names,
    {method name: (accesses, calls)})``."""
    lock_names = lock_attr_names(cls, imports)
    scans = {
        name: scan_method(fn, lock_names)
        for name, fn in methods.items()
    }
    return lock_names, scans


def thread_spawn_roles(cls: ast.ClassDef, methods: dict, imports) -> dict[str, str]:
    """Spawn-site inference: which methods of ``cls`` run on their
    own thread.  → ``{method name: role label}``.

    Four provable shapes (anything else — attr-chain targets,
    closures, externally-passed callables — has unknown provenance and
    stays silent):

    * ``threading.Thread(target=self.m, ...)`` resolved import-aware
      to the canonical ``threading.Thread``;
    * ``<self.ex>.submit(self.m, ...)`` where ``self.ex`` is a
      ctor-proven pool executor attr of the same class;
    * ``asyncio.create_task(self.m(...))`` / ``asyncio.ensure_future(
      self.m(...))`` / ``asyncio.run_coroutine_threadsafe(
      self.m(...), loop)`` resolved import-aware — the coroutine runs
      interleaved with every other task on the loop (awaits are the
      preemption points), so against a real THREAD its state shares
      exactly like a thread's, while two tasks on the same loop are
      cooperatively scheduled (FT017 models that with the implicit
      ``<event-loop>`` token);
    * ``<loop>.run_in_executor(executor, self.m, ...)`` — the method
      runs on a pool thread regardless of which loop or executor
      object carries it, so the receiver is not constrained.
    """
    executors = executor_attr_names(cls, imports)
    roles: dict[str, str] = {}
    for fn in methods.values():
        for node in walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            if imports.resolve_call(node) == "threading.Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        m = self_attr(kw.value)
                        if m is not None and m in methods:
                            roles[m] = f"thread({m})"
            if (imports.resolve_call(node) in (
                    "asyncio.create_task", "asyncio.ensure_future",
                    "asyncio.run_coroutine_threadsafe")
                    and node.args
                    and isinstance(node.args[0], ast.Call)):
                m = self_attr(node.args[0].func)
                if m is not None and m in methods:
                    roles[m] = f"task({m})"
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "submit"
                    and self_attr(f.value) in executors
                    and node.args):
                m = self_attr(node.args[0])
                if m is not None and m in methods:
                    roles[m] = f"worker({m})"
            if (isinstance(f, ast.Attribute)
                    and f.attr == "run_in_executor"
                    and len(node.args) >= 2):
                m = self_attr(node.args[1])
                if m is not None and m in methods:
                    roles[m] = f"executor({m})"
    return roles

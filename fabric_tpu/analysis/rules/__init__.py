"""Built-in rule battery — importing this package registers them all.

Rule ids are stable (baseline entries and noqa comments reference
them); slugs are the human-facing names:

    FT001 jit-purity             impure calls / mutation inside jit
    FT002 retrace-hazard         non-static Python values reaching jit
    FT003 host-sync-in-hot-path  device syncs on the validator path
    FT004 lock-discipline        lock-order cycles + blocking under lock
    FT005 swallowed-exception    broad except that drops the error
    FT006 union-env-coercion     env strings coercing non-scalar unions
    FT007 kernel-dtype-mismatch  int64 host arrays into int32 kernel lanes
    FT008 asyncio-task-leak      dropped ensure_future/create_task results
    FT009 unbounded-blocking-wait  no-timeout Future/Queue/Event/Thread waits
    FT010 unfinished-span        begin_block roots with no reachable finish
    FT011 device-buffer-lifetime  packed uploads pinned past their fetch
    FT012 pvtdata-purge-race     store writers racing the BTL purge walk
    FT013 metric-label-cardinality  per-request ids as metric labels
    FT014 nonce-reuse-hazard     random k nonces reaching sign calls
    FT015 resident-state-bypass  store writes skipping the residency
                                 cache's invalidation hook
    FT016 unattributed-device-sync  device syncs bypassing the launch
                                 ledger's attribution bracket
    FT017 cross-thread-state     self-attrs shared across thread roles
                                 with no common lock
    FT018 lost-update            unlocked read-modify-write of an attr
                                 the class guards elsewhere
    FT019 unruled-sharding       raw jax.sharding constructors outside
                                 the partition-rule layer
    FT020 clock-mixing           subtractions mixing time.time() with
                                 monotonic/perf_counter readings
"""

from fabric_tpu.analysis.rules import (  # noqa: F401
    asyncio_task_leak,
    blocking_wait,
    clock_mixing,
    cross_thread_state,
    device_buffer_lifetime,
    host_sync,
    jit_purity,
    kernel_dtype,
    lock_discipline,
    lost_update,
    metric_label_cardinality,
    nonce_reuse,
    pvtdata_purge_race,
    resident_bypass,
    retrace_hazard,
    swallowed_exception,
    unattributed_sync,
    unfinished_span,
    union_env,
    unruled_sharding,
)

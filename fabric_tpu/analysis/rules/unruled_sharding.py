"""FT019 unruled-sharding: raw sharding construction outside the
partition-rule layer.

The declarative partition-rule registry
(``fabric_tpu/parallel/mesh.py``) is the ONE place that decides how an
operand family splits over the device mesh: every ``NamedSharding`` /
``PartitionSpec`` a dispatch site needs comes from
``sharding_for(mesh, family, ndim)`` (or the ``shard``/``shard_batch``
wrappers), so the rules table stays the single source of truth — a
mesh resize, a replica axis, or a key-range re-partition is one
registry edit, not a hunt through every launch site.  A module that
builds ``jax.sharding.NamedSharding(...)`` by hand re-introduces the
ad-hoc layout the registry replaced: its operands silently diverge
from the table (wrong axis name, wrong replication) the first time the
mesh shape changes, and nothing fails until verdicts fork on a
multi-chip host.

Mechanics (strictly under-approximating, per the FT003..FT018
contract — a finding is always real), on the shared provenance
engine (:mod:`fabric_tpu.analysis.provenance`):

1. **Scope**: only modules under ``fabric_tpu/`` and NOT under
   ``fabric_tpu/parallel/`` are policed — the partition-rule layer is
   exactly where raw constructors belong, and out-of-package drivers
   (bench, scripts) are not part of the dispatch surface.
2. **The constructors**: any Call whose canonical dotted name
   (``ImportMap.resolve_call`` — import-aware, so a same-named local
   helper never matches) is ``jax.sharding.NamedSharding``,
   ``jax.sharding.PositionalSharding``,
   ``jax.sharding.PartitionSpec`` (including the conventional ``P``
   alias — alias resolution is the import map's job), or
   ``jax.experimental.shard_map.shard_map``.
3. No data-flow guessing: a sharding object that arrives as an
   argument, or a ``device_put`` whose sharding came from the
   registry, never flags — only the raw constructor call does.

Test code is exempt engine-wide — differentials pin layouts by hand
on purpose.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    register,
)
from fabric_tpu.analysis.provenance import module_index, walk_scope

#: canonical dotted names of the raw sharding constructors
_RAW_CANON = {
    "jax.sharding.NamedSharding",
    "jax.sharding.PositionalSharding",
    "jax.sharding.PartitionSpec",
    "jax.experimental.shard_map.shard_map",
}
_RULED_PREFIX = "fabric_tpu/parallel/"
_SCOPE_PREFIX = "fabric_tpu/"


@register
class UnruledShardingRule(Rule):
    id = "FT019"
    name = "unruled-sharding"
    severity = "error"
    description = (
        "flags raw jax.sharding constructor calls (NamedSharding / "
        "PositionalSharding / PartitionSpec / shard_map) in "
        "fabric_tpu modules outside the partition-rule layer "
        "(fabric_tpu/parallel/) — hand-built layouts silently diverge "
        "from the declarative rules table on mesh resize; route the "
        "operand through sharding_for(mesh, family, ndim)"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        rel = ctx.relpath.replace("\\", "/")
        if not rel.startswith(_SCOPE_PREFIX):
            return []
        if rel.startswith(_RULED_PREFIX):
            return []
        idx = module_index(ctx)
        imports = idx.imports
        if not imports.any_binding(lambda c: c.startswith("jax")):
            return []  # the module never imports jax at all
        out: list[Finding] = []
        # tree body + every function (methods included) + class bodies
        # — walk_scope never re-enters nested scopes, so each node is
        # visited exactly once
        for scope in [ctx.tree] + idx.functions + idx.classes:
            for node in walk_scope(scope):
                if not isinstance(node, ast.Call):
                    continue
                canon = imports.resolve_call(node)
                if canon not in _RAW_CANON:
                    continue
                short = canon.rsplit(".", 1)[-1]
                out.append(self.finding(
                    ctx, node.lineno, node.col_offset,
                    f"raw {short} construction ({canon}) outside the "
                    "partition-rule layer — this layout is invisible "
                    "to the fabric_tpu/parallel rules table and "
                    "diverges from it on mesh resize; use "
                    "sharding_for(mesh, family, ndim) / "
                    "shard(mesh, family, arr) so the operand family's "
                    "PartitionSpec stays declared in ONE place",
                ))
        return out

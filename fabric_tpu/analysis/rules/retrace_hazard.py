"""FT002 retrace-hazard: non-static Python values reaching jit.

Three shapes, all of which either retrace per call (silent 100×
slowdowns) or throw ``TypeError: unhashable`` the first time a static
argument varies:

* a jitted function with a mutable default (``def f(x, opts={})``) —
  the default is hashed as a static leaf or captured by the trace;
* a jitted closure reading a module-level list/dict that the module
  ALSO mutates — the trace bakes the first value and never sees the
  mutation;
* a call site passing a list/dict display to a parameter the jit
  marked static (``static_argnums``/``static_argnames``) — lists are
  unhashable, so the trace-cache lookup raises.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    dotted_name,
    register,
)
from fabric_tpu.analysis.rules._jit import find_jitted

_MUTATORS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault",
}


def _module_mutable_bindings(tree: ast.Module) -> dict[str, int]:
    """Top-level ``NAME = [...]`` / ``NAME = {...}`` bindings."""
    out: dict[str, int] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, (ast.List, ast.Dict, ast.Set)):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt.lineno
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.value, (ast.List, ast.Dict, ast.Set)):
            if isinstance(stmt.target, ast.Name):
                out[stmt.target.id] = stmt.lineno
    return out


def _mutated_names(tree: ast.Module) -> set[str]:
    """Names the module mutates in place anywhere (method mutators,
    subscript stores, aug-assigns)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            name = dotted_name(node.func.value)
            if name:
                out.add(name.split(".")[0])
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Subscript):
                    name = dotted_name(t.value)
                    if name:
                        out.add(name.split(".")[0])
    return out


@register
class RetraceHazardRule(Rule):
    id = "FT002"
    name = "retrace-hazard"
    severity = "error"
    description = (
        "flags mutable defaults on jitted functions, jitted closures "
        "over mutated module state, and unhashable static arguments"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        out: list[Finding] = []
        jitted = find_jitted(ctx.tree)
        if not jitted:
            return out
        mutable = _module_mutable_bindings(ctx.tree)
        mutated = _mutated_names(ctx.tree)

        for fname, jf in jitted.items():
            fn = jf.node
            # 1. mutable defaults
            args = list(fn.args.posonlyargs) + list(fn.args.args)
            defaults = fn.args.defaults
            for arg, default in zip(args[len(args) - len(defaults):], defaults):
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    out.append(self.finding(
                        ctx, default.lineno, default.col_offset,
                        f"jitted function '{fname}' has a mutable "
                        f"default for '{arg.arg}' — unhashable as a "
                        f"static leaf and stale once mutated",
                    ))
            for arg, default in zip(
                fn.args.kwonlyargs, fn.args.kw_defaults
            ):
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    out.append(self.finding(
                        ctx, default.lineno, default.col_offset,
                        f"jitted function '{fname}' has a mutable "
                        f"default for '{arg.arg}' — unhashable as a "
                        f"static leaf and stale once mutated",
                    ))
            # 2. closure over a mutated module-level list/dict
            param_names = {a.arg for a in args} | {
                a.arg for a in fn.args.kwonlyargs
            }
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in mutable
                    and node.id in mutated
                    and node.id not in param_names
                ):
                    out.append(self.finding(
                        ctx, node.lineno, node.col_offset,
                        f"jitted function '{fname}' closes over "
                        f"module-level '{node.id}' (a list/dict the "
                        f"module mutates) — the trace bakes the value "
                        f"at first call and never sees updates",
                    ))

        # 3. list/dict displays passed to static parameters
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = dotted_name(node.func)
            jf = jitted.get(cname or "")
            if jf is None or not (jf.static_argnums or jf.static_argnames):
                continue
            params = [a.arg for a in (
                list(jf.node.args.posonlyargs) + list(jf.node.args.args)
            )]
            for i, arg in enumerate(node.args):
                pname = params[i] if i < len(params) else None
                if (
                    i in jf.static_argnums
                    or (pname and pname in jf.static_argnames)
                ) and isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    out.append(self.finding(
                        ctx, arg.lineno, arg.col_offset,
                        f"unhashable {type(arg).__name__.lower()} literal "
                        f"passed to static parameter "
                        f"'{pname or i}' of jitted '{cname}' — the "
                        f"trace-cache lookup will raise TypeError",
                    ))
            for kw in node.keywords:
                if kw.arg in jf.static_argnames and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    out.append(self.finding(
                        ctx, kw.value.lineno, kw.value.col_offset,
                        f"unhashable {type(kw.value).__name__.lower()} "
                        f"literal passed to static parameter "
                        f"'{kw.arg}' of jitted '{cname}' — the "
                        f"trace-cache lookup will raise TypeError",
                    ))
        return out

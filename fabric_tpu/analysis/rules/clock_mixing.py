"""FT020 clock-mixing: latency arithmetic across clock domains.

Every latency instrument in this repo — the span tracer, the launch
ledger, the commit pipeline stage timers, the tx-flow journal's
milestone deltas (``observe/txflow.py``) — lives on ONE monotonic
clock (``time.perf_counter``/``time.monotonic``), because a duration
is only meaningful as the difference of two readings of the SAME
clock.  ``time.time()`` is a different domain: it has a different
epoch, and NTP slews and steps it at any moment, so
``time.time() - time.perf_counter()`` (or any cross-domain
subtraction) is not a duration — it is an arbitrary number that
silently drifts.  This is exactly the bug class that would corrupt
every milestone delta the tx-flow journal publishes while all the
arithmetic looks plausible, so the battery pins it mechanically.

Mechanics (strictly under-approximating, per the FT003..FT019
contract — a finding is always real), on the shared provenance
engine (:mod:`fabric_tpu.analysis.provenance`):

1. **Scope**: only modules under ``fabric_tpu/`` — out-of-package
   drivers (bench, scripts) may legitimately stamp wall-clock
   metadata; test code is exempt engine-wide.
2. **The subtraction**: any ``a - b`` where one operand PROVABLY
   canonicalizes to the monotonic family (``time.monotonic``,
   ``time.perf_counter``, their ``_ns`` variants) and the other to
   the wall family (``time.time``, ``time.time_ns``) — either
   direction.  Canonicalization is import-aware
   (``ImportMap.resolve_call`` — aliases and from-import renames
   tracked, a same-named local helper never matches) and follows
   ``int()``/``float()``/``round()``/``abs()`` wrappers plus at most
   one same-scope single-assignment local hop per side
   (``SingleAssignScope`` — every other binding form poisons).
3. Anything unprovable — parameters, attributes, cross-function
   flow, a local bound twice — stays silent: it may still be wrong,
   but the rule cannot prove it (the under-approximation contract).
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import Finding, ModuleCtx, Rule, register
from fabric_tpu.analysis.provenance import module_index, walk_scope

#: canonical dotted names per clock domain
_MONO = {
    "time.monotonic", "time.perf_counter",
    "time.monotonic_ns", "time.perf_counter_ns",
}
_WALL = {"time.time", "time.time_ns"}

#: value-preserving wrappers the provenance walk sees through
_WRAPPERS = {"int", "float", "round", "abs"}

_SCOPE_PREFIX = "fabric_tpu/"


@register
class ClockMixingRule(Rule):
    id = "FT020"
    name = "clock-mixing"
    severity = "error"
    description = (
        "flags subtractions mixing a time.time()-derived value with "
        "a time.monotonic()/perf_counter()-derived one — the clocks "
        "have different epochs and wall time is NTP-stepped, so the "
        "difference is not a duration; read both ends from the same "
        "monotonic clock"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        rel = ctx.relpath.replace("\\", "/")
        if not rel.startswith(_SCOPE_PREFIX):
            return []
        idx = module_index(ctx)
        imports = idx.imports
        if not imports.any_binding(
            lambda c: c.split(".")[0] == "time"
        ):
            return []  # the module never imports time at all
        out: list[Finding] = []
        # tree body + every function (methods included) + class
        # bodies — walk_scope never re-enters nested scopes, so each
        # Sub node is visited exactly once; scope-local provenance
        # comes from the enclosing function's tracker (module/class
        # bodies get their own)
        for scope in [ctx.tree] + idx.functions + idx.classes:
            tracker = idx.scope(scope)
            for node in walk_scope(scope):
                if not (isinstance(node, ast.BinOp)
                        and isinstance(node.op, ast.Sub)):
                    continue
                left = self._clock_of(node.left, tracker, imports, 0)
                right = self._clock_of(node.right, tracker, imports, 0)
                if left is None or right is None:
                    continue
                if left[0] == right[0]:
                    continue  # same domain: a real duration
                out.append(self.finding(
                    ctx, node.lineno, node.col_offset,
                    f"subtraction mixes clock domains: {left[1]} "
                    f"({left[0]}) vs {right[1]} ({right[0]}) — "
                    f"different epochs, and wall time is NTP-slewed "
                    f"mid-measurement, so this difference is not a "
                    f"duration; take both readings from the same "
                    f"monotonic clock (time.perf_counter)",
                ))
        out.sort(key=lambda f: (f.line, f.col))
        return out

    # -- provenance --------------------------------------------------------

    def _clock_of(self, node, tracker, imports, depth: int):
        """(domain, source) when ``node`` provably reads one clock
        family — "mono" or "wall" — else None."""
        if depth > 4:
            return None
        if isinstance(node, ast.Call):
            canon = imports.resolve_call(node)
            if canon in _MONO:
                return ("mono", canon)
            if canon in _WALL:
                return ("wall", canon)
            if (isinstance(node.func, ast.Name)
                    and node.func.id in _WRAPPERS
                    and node.func.id not in imports.local_defs
                    and node.args):
                return self._clock_of(node.args[0], tracker, imports,
                                      depth + 1)
            return None
        if isinstance(node, ast.Name):
            v = tracker.value_of(node.id)
            if v is not None:
                return self._clock_of(v, tracker, imports, depth + 1)
        return None

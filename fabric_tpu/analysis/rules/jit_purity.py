"""FT001 jit-purity: side effects inside jit-compiled functions.

A jitted function runs ONCE per (shape, dtype, static-arg) signature —
at trace time — and never again.  ``time.*`` / ``random.*`` /
``os.environ`` reads bake a single stale value into the compiled
graph; I/O happens once instead of per call; mutating closed-over
Python state desynchronizes host state from what the traced graph
saw.  These are exactly the bugs that pass a single-shape unit test
and corrupt production traffic after the first retrace.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    dotted_name,
    register,
)
from fabric_tpu.analysis.rules._jit import find_jitted, local_names

# call prefixes that are impure at trace time.  jax.random /
# np.random-free stdlib `random`, wall clocks, env reads, I/O.
_IMPURE_PREFIXES = (
    "time.", "random.", "np.random.", "numpy.random.",
    "os.environ", "os.getenv", "os.putenv", "os.urandom",
    "secrets.",
)
_IMPURE_CALLS = {"print", "open", "input"}
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault", "write",
}


def _impure_call(name: str | None) -> bool:
    if name is None:
        return False
    if name in _IMPURE_CALLS:
        return True
    return any(name.startswith(p) for p in _IMPURE_PREFIXES)


@register
class JitPurityRule(Rule):
    id = "FT001"
    name = "jit-purity"
    severity = "error"
    description = (
        "flags wall-clock/random/env/I-O calls and mutation of "
        "closed-over state inside jax.jit/pmap/shard_map functions"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        out: list[Finding] = []
        for fname, jf in find_jitted(ctx.tree).items():
            fn = jf.node
            locs = local_names(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    if _impure_call(name):
                        out.append(self.finding(
                            ctx, node.lineno, node.col_offset,
                            f"impure call '{name}' inside jitted "
                            f"function '{fname}' — traced once, then "
                            f"baked into the compiled graph",
                        ))
                        continue
                    # mutator method on a closed-over name
                    if (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS
                    ):
                        base = node.func.value
                        bname = dotted_name(base)
                        root = (bname or "").split(".")[0]
                        if root and root not in locs and not _is_module_ref(
                                root):
                            out.append(self.finding(
                                ctx, node.lineno, node.col_offset,
                                f"jitted function '{fname}' mutates "
                                f"closed-over '{bname}' via "
                                f".{node.func.attr}() — trace-time only; "
                                f"the compiled graph never re-runs it",
                            ))
                elif isinstance(node, (ast.Attribute, ast.Name)):
                    name = dotted_name(node)
                    if name and name.startswith("os.environ") and isinstance(
                            node.ctx, ast.Load):
                        out.append(self.finding(
                            ctx, node.lineno, node.col_offset,
                            f"os.environ read inside jitted function "
                            f"'{fname}' — evaluated at trace time only",
                        ))
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Subscript):
                            root = (dotted_name(t.value) or "").split(".")[0]
                            if root and root not in locs:
                                out.append(self.finding(
                                    ctx, t.lineno, t.col_offset,
                                    f"jitted function '{fname}' assigns "
                                    f"into closed-over "
                                    f"'{dotted_name(t.value)}[...]' — "
                                    f"runs at trace time only",
                                ))
        return _dedup(out)


def _is_module_ref(root: str) -> bool:
    # conservative: common module aliases never hold closure state
    return root in {"np", "jnp", "jax", "numpy", "math", "lax", "self"}


def _dedup(findings: list[Finding]) -> list[Finding]:
    seen: set[tuple] = set()
    out = []
    for f in findings:
        key = (f.path, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out

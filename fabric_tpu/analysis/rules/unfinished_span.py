"""FT010 unfinished-span: tracer block roots that never finalize.

``Tracer.begin_block`` returns a root span that only reaches the
flight recorder (and the watchdog, the /trace endpoint, the SLO
engine's finished-block stream) when ``finish_block`` runs on it —
and the tracer is deliberately fire-and-forget, so a dropped root
fails SILENTLY: the block commits fine, its trace just never exists.
The PR-7 sidecar server needed three separate ``finish_block`` call
sites (answer, error-answer, orphan teardown) to get this right; this
rule catches the shape where none is reachable at all.

Mechanics (strictly under-approximating, per the FT003..FT009
contract — a finding is always real):

1. **Creation sites** — calls whose attribute is ``begin_block``
   (``tracer.begin_block(...)``, ``self.tracer.begin_block(...)``,
   chained receivers included).  The name is unique to the tracer in
   this tree; a bare local ``def begin_block`` never produces an
   attribute call, so the FT003 same-name hazard does not arise.
2. **Leak test** — a creation site leaks when its root is

   * discarded outright (an expression statement — the tree can never
     finalize), or
   * bound to a plain local name whose every later Load is NEUTRAL:
     an argument to another span-family tracer call (``span``,
     ``add``, ``event``, ``set_attrs``, ``start``, ``end``,
     ``attach``, ``detach``) or a bare truth-test (``if root:``,
     ``root is None``).  Using a root only as a *parent* for child
     spans is exactly the silent-leak shape — children are recorded
     into a tree nothing will ever surface.

   Everything else is clean by under-approximation: a Load inside a
   ``finish_block(...)`` call finishes it; a Load in ANY other
   position — passed to a non-tracer call (``Request(root=root)``,
   ``executor.submit(fn, root)``, ``roots.append(root)``), returned,
   yielded, stored on an attribute/container, aliased — escapes,
   and the finish is assumed to happen wherever it went.
3. **Test code is exempt** (``tests/``, ``test_*.py``,
   ``conftest.py``) — fixtures construct half-open spans on purpose.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    register,
    walk_functions,
)

_BEGIN = "begin_block"
_FINISH = {"finish_block"}
#: tracer calls a root may feed WITHOUT counting as finished or
#: escaped — parenting children, annotating, thread adoption
_NEUTRAL = {"span", "add", "event", "set_attrs", "start", "end",
            "attach", "detach", _BEGIN}


def _is_begin_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == _BEGIN)


def _walk_own(scope: ast.AST):
    """A scope's OWN statements (nested defs are their own scopes via
    walk_functions — descending would double-count)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _call_attr(call: ast.Call) -> str:
    """The last attribute/name segment of a call's func ('' if
    unresolvable)."""
    name = call_name(call)
    if name is not None:
        return name.rsplit(".", 1)[-1]
    f = call.func
    return f.attr if isinstance(f, ast.Attribute) else ""


def _classify_loads(scope: ast.AST, name: str) -> tuple[bool, bool]:
    """(finished, escaped) over every Load of ``name`` in the scope's
    subtree (nested closures included — a closure that finishes the
    span counts, same as FT008's use test)."""
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(scope):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    finished = escaped = False
    for node in ast.walk(scope):
        if not (isinstance(node, ast.Name) and node.id == name
                and isinstance(node.ctx, ast.Load)):
            continue
        # walk up to the nearest Call that takes this Load as an
        # argument (directly or nested inside one of its arguments)
        cur: ast.AST = node
        call = None
        while True:
            parent = parents.get(id(cur))
            if parent is None or isinstance(parent, ast.stmt):
                break
            if isinstance(parent, ast.Call) and cur is not parent.func:
                call = parent
                break
            if isinstance(parent, ast.keyword):
                grand = parents.get(id(parent))
                if isinstance(grand, ast.Call):
                    call = grand
                break
            cur = parent
        if call is not None:
            attr = _call_attr(call)
            if attr in _FINISH:
                finished = True
            elif attr not in _NEUTRAL:
                escaped = True  # handed to non-tracer code
            continue
        # not a call argument: bare truth-tests are neutral, anything
        # else (return/yield/assign/container/attribute store rhs)
        # escapes — under-approximation keeps false positives at zero
        parent = parents.get(id(node))
        if isinstance(parent, (ast.Compare, ast.BoolOp, ast.UnaryOp,
                               ast.If, ast.While, ast.IfExp,
                               ast.Assert)):
            continue
        escaped = True
    return finished, escaped


@register
class UnfinishedSpanRule(Rule):
    id = "FT010"
    name = "unfinished-span"
    severity = "error"
    description = (
        "flags Tracer.begin_block roots that are discarded or only "
        "ever used as span parents — without a reachable finish_block "
        "the tree never hits the flight recorder, the watchdog, or "
        "the SLO engine's finished-block stream, and the loss is "
        "silent"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        rel = ctx.relpath
        base = rel.rsplit("/", 1)[-1]
        if ("tests/" in rel or rel.startswith("tests")
                or base.startswith("test_") or base == "conftest.py"):
            return []
        out: list[Finding] = []
        scopes = [ctx.tree] + list(walk_functions(ctx.tree))
        for scope in scopes:
            for node in _walk_own(scope):
                if isinstance(node, ast.Expr) and _is_begin_call(
                        node.value):
                    out.append(self.finding(
                        ctx, node.lineno, node.col_offset,
                        "the root span returned by begin_block is "
                        "discarded — the block's tree can never be "
                        "finish_block'd into the flight recorder; "
                        "bind it and finish it on every path (or pass "
                        "it to the code that will)",
                    ))
                elif (isinstance(node, ast.Assign)
                      and len(node.targets) == 1
                      and isinstance(node.targets[0], ast.Name)
                      and _is_begin_call(node.value)):
                    tgt = node.targets[0].id
                    finished, escaped = _classify_loads(scope, tgt)
                    if not finished and not escaped:
                        out.append(self.finding(
                            ctx, node.lineno, node.col_offset,
                            f"the root span bound to '{tgt}' is never "
                            "passed to finish_block and never escapes "
                            "this function — child spans recorded "
                            "under it land in a tree that will never "
                            "reach the flight recorder, the slow-block "
                            "watchdog, or the SLO stream; call "
                            "finish_block on every path (the sidecar "
                            "server needs it on the answer, "
                            "error-answer, AND orphan paths)",
                        ))
        return out

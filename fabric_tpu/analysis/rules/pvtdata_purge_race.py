"""FT012 pvtdata-purge-race: store writers racing the BTL purge walk.

The pvtdata/transient stores (``ledger/pvtdata.py``,
``peer/transient.py``) share ONE sqlite connection each
(``check_same_thread=False``) between their writers (``persist``,
``resolve_missing``, ``commit_block``) and their purge walks
(``purge_expired`` — the BTL expiry SELECT-then-DELETE whose returned
rows drive the private-STATE erase — and ``purge_below``).  The purge
walk is not atomic against a concurrent writer: a row inserted between
the walk's SELECT and its DELETE is deleted without ever being
returned, so the corresponding private state is never erased (or, for
the transient store, endorsement cleartext written during the walk is
silently dropped below the retention line).  The repo's discipline is
that writers and purges serialize on the event-loop thread / the
commit lock; this rule polices the discipline.

Mechanics (strictly under-approximating, per the FT003..FT011
contract — a finding is always real):

1. **Family match by receiver** — within one function scope, find
   attribute calls ``<recv>.purge_expired(...)`` /
   ``<recv>.purge_below(...)`` and attribute uses of
   ``<recv>.persist`` / ``<recv>.resolve_missing`` /
   ``<recv>.commit_block`` where ``<recv>`` is the SAME dotted
   receiver (``self.transient``, ``store``, ``ch.ledger.pvtdata``).
   The receiver pairing is what keeps the writer names honest:
   ``commit_block`` exists on ledgers and block stores too, but only
   the pvt stores also have a purge method on the same object.
2. **Concurrent dispatch** — flag only when at least one of the two
   family uses is handed to another thread, resolved IMPORT-AWARE
   (the FT003 lesson — a same-named local helper never matches):

   * ``threading.Thread(...)`` (module alias or bare from-import),
   * ``<executor>.submit(...)`` where the executor local was assigned
     from ``ThreadPoolExecutor``/``ProcessPoolExecutor``
     (concurrent.futures, aliases and from-imports tracked),
   * ``<loop>.run_in_executor(...)``,
   * ``asyncio.run_coroutine_threadsafe(...)`` / ``asyncio.to_thread
     (...)`` (aliases and from-imports tracked).

   A family use *inside a dispatcher call's arguments* (a bound
   method reference, or a use inside a ``lambda`` argument) counts as
   dispatched.  Both-inline uses never flag — same-thread sequencing
   is exactly the discipline.
3. **Test code is exempt** (``tests/``, ``test_*.py``,
   ``conftest.py``) — tests race writers against the purge walk on
   purpose to pin recovery behavior.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    dotted_name,
    register,
    walk_functions,
)

_PURGE = {"purge_expired", "purge_below"}
_WRITERS = {"persist", "resolve_missing", "commit_block"}
_EXECUTOR_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_ASYNCIO_DISPATCH = {"run_coroutine_threadsafe", "to_thread"}


def _bindings(tree: ast.Module):
    """Import map: (threading aliases, asyncio aliases,
    concurrent.futures aliases, bare Thread names, bare asyncio
    dispatch names, bare executor ctor names)."""
    threading_alias: set[str] = set()
    asyncio_alias: set[str] = set()
    cf_alias: set[str] = set()
    bare_thread: set[str] = set()
    bare_async: set[str] = set()
    bare_ctor: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                base = a.asname or a.name.split(".")[0]
                if a.name == "threading":
                    threading_alias.add(base)
                elif a.name == "asyncio":
                    asyncio_alias.add(base)
                elif a.name in ("concurrent.futures", "concurrent"):
                    cf_alias.add(base)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                name = a.asname or a.name
                if mod == "threading" and a.name == "Thread":
                    bare_thread.add(name)
                elif mod == "asyncio" and a.name in _ASYNCIO_DISPATCH:
                    bare_async.add(name)
                elif (mod == "concurrent.futures"
                        and a.name in _EXECUTOR_CTORS):
                    bare_ctor.add(name)
    return (threading_alias, asyncio_alias, cf_alias, bare_thread,
            bare_async, bare_ctor)


def _walk_own(scope: ast.AST):
    """A scope's own nodes; nested function defs are their own scopes
    (lambdas are NOT skipped — a lambda handed to a dispatcher runs on
    the dispatcher's thread and belongs to this scope's analysis)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _executor_locals(scope: ast.AST, cf_alias: set, bare_ctor: set) -> set:
    """Local names assigned from ThreadPoolExecutor/ProcessPoolExecutor
    calls (import-aware) — their ``.submit`` dispatches to a worker."""
    out: set[str] = set()
    for node in _walk_own(scope):
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        name = dotted_name(node.value.func)
        if name is None:
            continue
        parts = name.split(".")
        if len(parts) == 1 and parts[0] in bare_ctor:
            out.add(node.targets[0].id)
        elif (len(parts) >= 2 and parts[0] in cf_alias
                and parts[-1] in _EXECUTOR_CTORS):
            out.add(node.targets[0].id)
    return out


def _is_dispatcher(call: ast.Call, binds, executor_locals: set) -> bool:
    (threading_alias, asyncio_alias, _cf, bare_thread, bare_async,
     _bc) = binds
    name = dotted_name(call.func)
    if name is None:
        return False
    parts = name.split(".")
    if len(parts) == 1:
        return parts[0] in bare_thread or parts[0] in bare_async
    if len(parts) == 2:
        head, attr = parts
        if head in threading_alias and attr == "Thread":
            return True
        if head in asyncio_alias and attr in _ASYNCIO_DISPATCH:
            return True
        if head in executor_locals and attr == "submit":
            return True
        if attr == "run_in_executor":
            # loop.run_in_executor: the attr name is asyncio-specific
            # enough that any receiver is a real event loop in practice
            return True
    return False


def _family_uses(scope: ast.AST, binds, executor_locals: set):
    """→ {recv: {"purge": [(line, dispatched)],
                 "write": [(line, dispatched)]}} over the scope.

    ``dispatched`` = the use sits inside a dispatcher call's argument
    subtree (bound-method handoff or lambda body)."""
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(scope):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node

    def dispatched(node: ast.AST) -> bool:
        cur = node
        while True:
            parent = parents.get(id(cur))
            if parent is None or isinstance(
                    parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            if (isinstance(parent, ast.Call) and cur is not parent.func
                    and _is_dispatcher(parent, binds, executor_locals)):
                return True
            cur = parent

    out: dict[str, dict] = {}
    for node in _walk_own(scope):
        if not isinstance(node, ast.Attribute):
            continue
        recv = dotted_name(node.value)
        if recv is None:
            continue
        if node.attr in _PURGE:
            kind = "purge"
        elif node.attr in _WRITERS:
            kind = "write"
        else:
            continue
        entry = out.setdefault(recv, {"purge": [], "write": []})
        entry[kind].append((node.lineno, dispatched(node)))
    return out


@register
class PvtdataPurgeRaceRule(Rule):
    id = "FT012"
    name = "pvtdata-purge-race"
    severity = "error"
    description = (
        "flags pvt/transient store writers (persist / resolve_missing "
        "/ commit_block) dispatched to another thread while the same "
        "store's BTL purge walk (purge_expired / purge_below) runs in "
        "the same scope — the walk's SELECT-then-DELETE is not atomic "
        "against concurrent writers on the shared sqlite connection"
    )

    def check_module(self, ctx: ModuleCtx) -> list[Finding]:
        rel = ctx.relpath
        base = rel.rsplit("/", 1)[-1]
        if ("tests/" in rel or rel.startswith("tests")
                or base.startswith("test_") or base == "conftest.py"):
            return []
        binds = _bindings(ctx.tree)
        out: list[Finding] = []
        for scope in [ctx.tree] + list(walk_functions(ctx.tree)):
            executor_locals = _executor_locals(scope, binds[2], binds[5])
            for recv, uses in _family_uses(
                    scope, binds, executor_locals).items():
                purges, writes = uses["purge"], uses["write"]
                if not purges or not writes:
                    continue
                if not any(d for _l, d in purges + writes):
                    continue  # both inline = serialized by the thread
                wline = min(l for l, _d in writes)
                for pline, _d in sorted(set(purges)):
                    out.append(self.finding(
                        ctx, pline, 0,
                        f"'{recv}' purge walk races a writer "
                        f"dispatched to another thread in this scope "
                        f"(writer at line {wline}): the walk's "
                        "SELECT-then-DELETE is not atomic against "
                        "concurrent inserts on the shared sqlite "
                        "connection — a row written mid-walk is "
                        "purged without its state erase (or dropped "
                        "below the retention line); serialize both "
                        "on one thread/lock or move them onto the "
                        "same executor",
                    ))
        return out

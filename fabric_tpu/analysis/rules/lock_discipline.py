"""FT004 lock-discipline: acquisition-order cycles + blocking under lock.

Two checks over every ``with`` / ``async with`` whose context manager
looks like a lock (``*.reader()`` / ``*.writer()`` on the
utils.locks.AsyncRWLock seam, or a bare ``*lock*``-named attribute):

* **order**: nested acquisitions produce directed edges
  (outer → inner) into one project-wide graph; any cycle means two
  code paths can acquire the same pair of locks in opposite order —
  the classic deadlock that only fires under production interleaving.
* **blocking-under-lock**: synchronous blocking calls
  (``os.fsync``, ``time.sleep``, ``<future>.result()``,
  ``run_until_complete``, ``subprocess.*``, gRPC stubs) made while a
  lock is held stall every other endorser/committer queued on it.

Lock identity is textual (the trailing attribute of the lock
expression: ``self.commit_lock.writer()`` → ``commit_lock``), which is
exactly right for a codebase with a handful of named locks and wrong
in ways a noqa comment can absorb.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import (
    Finding,
    ModuleCtx,
    Rule,
    call_name,
    dotted_name,
    register,
)

_RW_METHODS = {"reader", "writer", "acquire"}
_BLOCKING_CALLS = {
    "os.fsync", "time.sleep", "run_until_complete",
    "subprocess.run", "subprocess.check_output", "subprocess.call",
    "socket.create_connection",
}
_BLOCKING_ATTRS = {"result", "run_until_complete"}


def _lock_id(expr: ast.AST) -> str | None:
    """Lock name for a with-item context expr, or None if it doesn't
    look like a lock."""
    # with lock.reader() / lock.writer() / lock.acquire()
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute):
        if expr.func.attr in _RW_METHODS:
            base = dotted_name(expr.func.value)
            if base:
                return base.split(".")[-1]
        return None
    # with self._lock: / with commit_mutex:
    name = dotted_name(expr)
    if name:
        leaf = name.split(".")[-1]
        if "lock" in leaf.lower() or "mutex" in leaf.lower():
            return leaf
    return None


def _is_blocking(node: ast.Call) -> str | None:
    name = call_name(node)
    if name in _BLOCKING_CALLS:
        return name
    if (
        isinstance(node.func, ast.Attribute)
        and node.func.attr in _BLOCKING_ATTRS
        and not node.args and not node.keywords
    ):
        base = dotted_name(node.func.value) or "<expr>"
        return f"{base}.{node.func.attr}"
    return None


class _LockWalker(ast.NodeVisitor):
    """Collect (outer → inner) edges and blocking calls per module."""

    def __init__(self, rule: Rule, ctx: ModuleCtx):
        self.rule = rule
        self.ctx = ctx
        self.stack: list[str] = []
        self.edges: dict[tuple[str, str], tuple] = {}  # → first location
        self.findings: list[Finding] = []

    def _visit_with(self, node):
        acquired: list[str] = []
        for item in node.items:
            lock = _lock_id(item.context_expr)
            if lock is not None:
                if self.stack:
                    edge = (self.stack[-1], lock)
                    self.edges.setdefault(
                        edge, (self.ctx, node.lineno, node.col_offset)
                    )
                self.stack.append(lock)
                acquired.append(lock)
        self.generic_visit(node)
        for _ in acquired:
            self.stack.pop()

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Call(self, node: ast.Call):
        if self.stack:
            blocked = _is_blocking(node)
            if blocked is not None:
                self.findings.append(self.rule.finding(
                    self.ctx, node.lineno, node.col_offset,
                    f"blocking call '{blocked}()' while holding lock "
                    f"'{self.stack[-1]}' — stalls every waiter queued "
                    f"on it",
                ))
        self.generic_visit(node)


@register
class LockDisciplineRule(Rule):
    id = "FT004"
    name = "lock-discipline"
    severity = "error"
    description = (
        "builds a project-wide lock-acquisition graph and flags "
        "order cycles plus blocking calls made while a lock is held"
    )

    def check_project(self, modules: list[ModuleCtx]) -> list[Finding]:
        out: list[Finding] = []
        edges: dict[tuple[str, str], tuple] = {}
        for mod in modules:
            w = _LockWalker(self, mod)
            w.visit(mod.tree)
            out.extend(w.findings)
            for edge, loc in w.edges.items():
                edges.setdefault(edge, loc)

        # cycle detection over the project-wide order graph
        graph: dict[str, set[str]] = {}
        for a, b in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        for (a, b), (ctx, line, col) in sorted(
            edges.items(), key=lambda kv: (kv[1][0].relpath, kv[1][1])
        ):
            if a == b:
                out.append(self.finding(
                    ctx, line, col,
                    f"lock '{a}' re-acquired while already held — "
                    f"self-deadlock on a non-reentrant lock",
                ))
            elif self._reaches(graph, b, a):
                out.append(self.finding(
                    ctx, line, col,
                    f"lock-order cycle: '{a}' is acquired while "
                    f"holding '{b}' elsewhere, and here '{b}' is "
                    f"acquired while holding '{a}' — opposite orders "
                    f"deadlock under contention",
                ))
        return out

    @staticmethod
    def _reaches(graph: dict[str, set[str]], src: str, dst: str) -> bool:
        seen = {src}
        stack = [src]
        while stack:
            cur = stack.pop()
            for nxt in graph.get(cur, ()):
                if nxt == dst:
                    return True
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return False

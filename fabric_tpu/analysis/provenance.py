"""Shared provenance engine for the analysis battery.

Every project rule used to carry its own copy of the same three
dataflow fragments: an import table (module aliases + from-import
renames, with local defs shadowing), a single-assignment local
tracker (with poisoning of every other binding form — the FT014
review-pass semantics), and a self-attr scan over class bodies.
This module extracts them once, plus a per-module symbol index that
is built on first use and cached on the :class:`ModuleCtx`, so
project-wide rules stop re-walking every tree per rule.

The engine preserves the battery's under-approximation contract:
every resolver answers "provably yes" or "unknown" — a rule that
stays silent on "unknown" can only lose findings by porting onto it,
never invent them.
"""

from __future__ import annotations

import ast

from fabric_tpu.analysis.core import dotted_name

# -- import-aware alias resolution ------------------------------------------


class ImportMap:
    """Canonical dotted names for a module's import bindings.

    ==============================================  =======================
    binding                                         canonical
    ==============================================  =======================
    ``import secrets``                              secrets → secrets
    ``import random as rnd``                        rnd → random
    ``import jax.numpy as jnp``                     jnp → jax.numpy
    ``import a.b.c`` (no asname)                    a → a
    ``from secrets import randbelow as below``      below → secrets.randbelow
    ``from fabric_tpu.observe import ledger``       ledger → fabric_tpu.observe.ledger
    ==============================================  =======================

    A ``def``/``class`` anywhere in the module SHADOWS the binding
    (the FT003 lesson: a same-named local helper never matches), and
    relative imports resolve to nothing — both answer None.
    """

    def __init__(self, tree: ast.AST):
        self._names: dict[str, str] = {}
        self.local_defs: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self._names[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        self._names[root] = root
            elif isinstance(node, ast.ImportFrom):
                if node.level:  # relative: canonical unknown
                    continue
                mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    self._names[a.asname or a.name] = (
                        f"{mod}.{a.name}" if mod else a.name
                    )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                self.local_defs.add(node.name)

    def resolve(self, name: str) -> str | None:
        """Local name → canonical dotted path (None = unbound or
        shadowed by a local def)."""
        if name in self.local_defs:
            return None
        return self._names.get(name)

    def resolve_dotted(self, dotted: str | None) -> str | None:
        """``"rnd.randrange"`` → ``"random.randrange"`` (the root is
        resolved, the attribute tail rides along)."""
        if not dotted:
            return None
        root, _, rest = dotted.partition(".")
        canon = self.resolve(root)
        if canon is None:
            return None
        return f"{canon}.{rest}" if rest else canon

    def resolve_node(self, node: ast.AST) -> str | None:
        """Name/Attribute chain → canonical dotted path."""
        return self.resolve_dotted(dotted_name(node))

    def resolve_call(self, call: ast.Call) -> str | None:
        return self.resolve_node(call.func)

    def any_binding(self, pred) -> bool:
        """True when any live (unshadowed) binding's canonical path
        satisfies ``pred`` — the cheap "does this module even import
        the subsystem" arming check."""
        return any(
            pred(canon) for name, canon in self._names.items()
            if name not in self.local_defs
        )


# -- scope walking + the single-assignment tracker --------------------------


def walk_scope(scope: ast.AST):
    """Every node belonging to ``scope`` itself — nested function /
    class / lambda bodies are their own scopes and are not entered."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class SingleAssignScope:
    """One scope's single-assignment locals (the FT014 review-pass
    semantics, extracted).  ``single[name]`` is the value expression
    of a local bound by EXACTLY one plain ``name = expr`` statement.
    EVERY other binding form — tuple/starred unpacking, aug/ann
    assignment, for targets, comprehensions, walrus, ``with ... as``
    — POISONS the name: its value is then unprovable and a rule
    consuming the scope stays silent (the under-approximation
    contract; a k rebound by ``k, tag = ...`` after a random seed
    must NOT count as the random value)."""

    def __init__(self, scope: ast.AST):
        counts: dict[str, int] = {}
        values: dict[str, ast.expr] = {}

        def poison(target):
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name):
                    counts[sub.id] = counts.get(sub.id, 0) + 99

        for node in walk_scope(scope):
            if isinstance(node, ast.Assign):
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    t = node.targets[0]
                    counts[t.id] = counts.get(t.id, 0) + 1
                    values[t.id] = node.value
                else:
                    for t in node.targets:
                        poison(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign,
                                   ast.For, ast.AsyncFor,
                                   ast.comprehension, ast.NamedExpr)):
                poison(node.target)
            elif isinstance(node, ast.withitem):
                if node.optional_vars is not None:
                    poison(node.optional_vars)
        self.single: dict[str, ast.expr] = {
            n: v for n, v in values.items() if counts.get(n) == 1
        }

    def value_of(self, name: str) -> ast.expr | None:
        return self.single.get(name)

    def names_where(self, pred) -> set[str]:
        """Single-assignment locals whose value expression satisfies
        ``pred`` — the "local provably bound from X" query."""
        return {n for n, v in self.single.items() if pred(v)}


# -- class self-attr tracking -----------------------------------------------


def class_self_attrs(cls: ast.ClassDef, value_pred) -> set[str]:
    """``self.<attr>`` names assigned anywhere in the class whose
    assigned value satisfies ``value_pred`` (the repo's
    ``self._ctr = registry.counter(...)`` idiom)."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and value_pred(node.value)):
            out.add(t.attr)
    return out


# -- the per-module symbol index --------------------------------------------


class ModuleIndex:
    """Everything a rule asks of one parsed module, computed once:
    the import map, the function/class lists, method ownership, and
    memoized :class:`SingleAssignScope` trackers per scope.  Obtain
    through :func:`module_index`, which caches the instance on the
    ``ModuleCtx`` — N project rules share one walk."""

    def __init__(self, ctx):
        self.ctx = ctx
        tree = ctx.tree
        self.imports = ImportMap(tree)
        self.functions = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self.classes = [
            n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
        ]
        # direct methods per class (last def wins, like the runtime)
        self._class_methods: dict[int, dict] = {}
        # enclosing class for EVERY function under a class, nested
        # defs included; outermost class wins for nested classes
        self._enclosing: dict[int, ast.ClassDef] = {}
        for cls in self.classes:
            methods: dict[str, ast.AST] = {}
            for child in ast.iter_child_nodes(cls):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    methods[child.name] = child
            self._class_methods[id(cls)] = methods
            for sub in ast.walk(cls):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    self._enclosing.setdefault(id(sub), cls)
        self._scopes: dict[int, SingleAssignScope] = {}

    def class_methods(self, cls: ast.ClassDef) -> dict[str, ast.AST]:
        return self._class_methods[id(cls)]

    def enclosing_class(self, fn: ast.AST) -> ast.ClassDef | None:
        return self._enclosing.get(id(fn))

    def scope(self, node: ast.AST) -> SingleAssignScope:
        s = self._scopes.get(id(node))
        if s is None:
            s = self._scopes[id(node)] = SingleAssignScope(node)
        return s


def module_index(ctx) -> ModuleIndex:
    """The cached :class:`ModuleIndex` for a ``ModuleCtx`` (built on
    first use; every rule after that shares it)."""
    idx = getattr(ctx, "_prov_index", None)
    if idx is None or idx.ctx is not ctx:
        idx = ModuleIndex(ctx)
        ctx._prov_index = idx
    return idx

"""Chain replay: the full-occupancy catch-up driver.

Every workload the peer serves live is open-loop — blocks arrive with
gaps, so the depth-N ``CommitPipeline`` (peer/pipeline.py) never shows
its ceiling.  Catch-up is the closed-loop case: a joining or restarted
peer holds (or can pull) the whole chain suffix and wants it validated
back-to-back.  This module feeds the EXISTING commit machinery from a
block source with zero inter-block think time:

* **prefetch-ahead decode** — a dedicated reader thread pulls blocks
  from the source iterator (a ``BlockStore.iter_blocks`` generator
  reads + proto-decodes lazily, so the file read and unmarshal run on
  the reader, never on the submit path) into a bounded queue;
* **bounded in-flight window** — the caller thread drains the queue
  into ``CommitPipeline.submit`` at the full configured depth; the
  pipeline's own window bounds device + commit in-flight work, the
  queue bounds decoded-but-unsubmitted blocks;
* **progress checkpointing by height** — the committer-side wrapper
  journals the last committed height (atomic tmp+rename JSON) every
  ``checkpoint_every`` blocks, so a killed replay resumes exactly
  where it stopped.  The DESTINATION ledger is the authority —
  ``KVLedger.commit_block`` refuses out-of-order numbers, so a resume
  can never double-apply; the checkpoint file is the cheap,
  crash-readable progress record for operators and drivers that do
  not hold the ledger open.

Replay is throughput-mode traffic: the driver takes a hold on the
traffic autopilot (``Autopilot.hold_throughput``) for its duration so
the shed/BUSY and weight-halving overload rules — tuned for open-loop
tenant arrivals — do not fire on a closed-loop feed whose queue is
SUPPOSED to be full.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time

_log = logging.getLogger("fabric_tpu.replay")

#: reader → submit handoff bound: decoded blocks held ahead of the
#: pipeline.  Small — the pipeline's depth window is the real
#: in-flight bound; this only needs to hide one read+decode latency.
DEFAULT_PREFETCH = 8

#: checkpoint cadence (blocks).  Aligned with the blockstore's default
#: group-commit window so a checkpoint never claims heights an fsync
#: window could still lose.
DEFAULT_CHECKPOINT_EVERY = 8

_POLL_S = 5.0  # bounded-wait poll for queue handoffs (FT009)


class ReplayCheckpoint:
    """Crash-readable replay progress: ``{"height": H}`` meaning
    blocks ``< H`` are committed.  Written atomically (tmp + rename)
    from the committer side; read at resume."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> int | None:
        try:
            with open(self.path) as f:
                return int(json.load(f)["height"])
        except (OSError, ValueError, KeyError):
            return None

    def save(self, height: int) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"height": int(height)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)


class ReplayDriver:
    """Drive a ``CommitPipeline`` from a block iterator at full depth.

    ``validator`` / ``commit_fn`` are exactly the pipeline's
    contract (peer/pipeline.py) — the driver adds the reader thread,
    the checkpoint journal, and the autopilot throughput hold.  One
    driver instance runs one ``run()``; build a fresh one to resume.
    """

    def __init__(self, validator, commit_fn, *, depth: int = 4,
                 prefetch: int = DEFAULT_PREFETCH,
                 checkpoint: ReplayCheckpoint | str | None = None,
                 checkpoint_every: int = DEFAULT_CHECKPOINT_EVERY,
                 pre_launch_fn=None, channel: str = "",
                 coalesce_blocks: int = 0, tracer=None, autopilot=None,
                 pipe_hook=None):
        self.validator = validator
        self.depth = max(1, int(depth))
        self.prefetch = max(1, int(prefetch))
        if isinstance(checkpoint, str):
            checkpoint = ReplayCheckpoint(checkpoint)
        self.checkpoint = checkpoint
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.pre_launch_fn = pre_launch_fn
        self.channel = channel
        self.coalesce_blocks = int(coalesce_blocks)
        self.tracer = tracer
        self._autopilot = autopilot
        # optional pipe exposure hook: called with the live
        # CommitPipeline at start and None at teardown, so a hosting
        # PeerChannel can route autopilot runtime knobs (depth,
        # coalesce) at it while the replay runs
        self._pipe_hook = pipe_hook
        self._inner_commit = commit_fn
        # committed-progress state: mutated ONLY on the pipeline's
        # committer thread (commit_fn is serialized there), read by
        # the run() thread after close() joins it
        self._committed_blocks = 0
        self._committed_txs = 0
        self._last_height: int | None = None
        self._stop = threading.Event()

    # -- committer-side wrapper ---------------------------------------------

    def _commit(self, res):
        self._inner_commit(res)
        self._committed_blocks += 1
        self._committed_txs += res.n_valid
        h = res.block.header.number + 1
        self._last_height = h
        if (self.checkpoint is not None
                and self._committed_blocks % self.checkpoint_every == 0):
            self.checkpoint.save(h)

    # -- the drive loop -----------------------------------------------------

    def run(self, blocks, start: int | None = None) -> dict:
        """Replay ``blocks`` (an iterator of decoded Block protos —
        e.g. ``store.iter_blocks(h)``) through the pipeline.  Blocks
        numbered below ``start`` are skipped without validation (the
        resume path hands the full iterator and the committed
        height).  Returns the replay stats dict."""
        from fabric_tpu.peer.pipeline import CommitPipeline

        ap = self._autopilot
        if ap is None:
            from fabric_tpu.control.autopilot import global_autopilot

            ap = global_autopilot()
        if ap is not None:
            ap.hold_throughput()
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        reader_exc: list = []

        def reader():
            # the prefetch-ahead decode stage: the source iterator's
            # file read + proto unmarshal run HERE, overlapped with
            # the submit thread's device launches
            try:
                for blk in blocks:
                    if start is not None and blk.header.number < start:
                        continue
                    while not self._stop.is_set():
                        try:
                            q.put(blk, timeout=_POLL_S)
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # surfaced after the drain
                reader_exc.append(e)
            finally:
                while not self._stop.is_set():
                    try:
                        q.put(None, timeout=_POLL_S)
                        break
                    except queue.Full:
                        continue

        rt = threading.Thread(target=reader, name="fabtpu-replay-read",
                              daemon=True)
        pipe = CommitPipeline(
            self.validator, self._commit, depth=self.depth,
            pre_launch_fn=self.pre_launch_fn, channel=self.channel,
            coalesce_blocks=self.coalesce_blocks, tracer=self.tracer,
            replay=True,
        )
        if self._pipe_hook is not None:
            self._pipe_hook(pipe)
        t0 = time.perf_counter()
        submitted = 0
        try:
            rt.start()
            while True:
                try:
                    blk = q.get(timeout=_POLL_S)
                except queue.Empty:
                    if not rt.is_alive():
                        break  # reader died without its sentinel
                    continue
                if blk is None:
                    break
                if self.coalesce_blocks >= 2:
                    # opportunistic launch coalescing over the decoded
                    # backlog (no wait — only blocks already queued)
                    group, ended = [blk], False
                    while len(group) < self.coalesce_blocks:
                        try:
                            nxt = q.get_nowait()
                        except queue.Empty:
                            break
                        if nxt is None:
                            ended = True
                            break
                        group.append(nxt)
                    if len(group) == 1:
                        pipe.submit(blk)
                    else:
                        pipe.submit_many(group)
                    submitted += len(group)
                    if ended:
                        break
                else:
                    pipe.submit(blk)
                    submitted += 1
        except BaseException:
            # quarantine-and-stop, like the deliver driver: the
            # checkpoint + destination height already record exactly
            # where to resume
            self._stop.set()
            pipe.close(flush=False)
            if pipe.last_failure is not None:
                num, stage = pipe.last_failure
                _log.warning(
                    "%s: replay stopped at a %s-stage failure on "
                    "block %s; committed height %s", self.channel,
                    stage, num, self._last_height,
                )
            raise
        else:
            pipe.close()  # flush the verified tail
            if reader_exc:
                raise reader_exc[0]
        finally:
            if self._pipe_hook is not None:
                self._pipe_hook(None)
            self._stop.set()
            rt.join(timeout=_POLL_S)
            if rt.is_alive():
                _log.warning("%s: replay reader did not stop",
                             self.channel)
            if (self.checkpoint is not None
                    and self._last_height is not None):
                self.checkpoint.save(self._last_height)
            if ap is not None:
                ap.release_throughput()
        dt = time.perf_counter() - t0
        stats = {
            "blocks": self._committed_blocks,
            "txs_valid": self._committed_txs,
            "submitted": submitted,
            "seconds": round(dt, 4),
            "blocks_per_s": round(self._committed_blocks / dt, 2)
            if dt > 0 else None,
            "tx_per_s": round(self._committed_txs / dt, 1)
            if dt > 0 else None,
            "height": self._last_height,
            "depth": self.depth,
        }
        if self.tracer is not None and self.depth > 1:
            try:
                from fabric_tpu import observe

                cov = observe.coverage_from_roots(
                    self.tracer.recent_roots(),
                    window=max(1, self.depth - 1),
                )
                cov.pop("per_block", None)
                stats["pipeline_overlap_coverage"] = cov
            except Exception as e:
                _log.debug("replay coverage unavailable: %s", e)
        return stats


def replay_into(ledger, validator, source_store, *, depth: int = 4,
                prefetch: int = DEFAULT_PREFETCH,
                checkpoint: ReplayCheckpoint | str | None = None,
                checkpoint_every: int = DEFAULT_CHECKPOINT_EVERY,
                coalesce_blocks: int = 0, tracer=None,
                autopilot=None) -> dict:
    """Catch ``ledger`` (KVLedger) up from ``source_store`` (a
    BlockStore holding the chain) — the local-store replay shape the
    bench, the smoke and ``peer --replay-from`` share.

    Resume comes from the DESTINATION: ``ledger.blocks.height`` names
    the next block to validate, and ``commit_block``'s in-order check
    makes a double-apply structurally impossible.  The commit wiring
    is the bench/peer standard: tx_filter + batch + history + txids +
    hd_bytes through ``KVLedger.commit_block``."""

    def commit_fn(res):
        ledger.commit_block(res.block, res.tx_filter, res.batch,
                            res.history, None, res.txids,
                            res.pend.hd_bytes)

    start = ledger.blocks.height
    drv = ReplayDriver(
        validator, commit_fn, depth=depth, prefetch=prefetch,
        checkpoint=checkpoint, checkpoint_every=checkpoint_every,
        coalesce_blocks=coalesce_blocks, tracer=tracer,
        autopilot=autopilot,
    )
    stats = drv.run(source_store.iter_blocks(start), start=start)
    stats["resumed_from"] = start
    return stats

"""Chaincode runtime: contract execution building rwsets via the
simulator.

The reference launches chaincode out-of-process (Docker or external
service) and speaks a duplex gRPC FSM
(core/chaincode/chaincode_support.go:160 Execute, handler.go:364
ProcessStream — GetState/PutState round-trips per call).  Two modes
here, matching its external-builder direction but without Docker:

* **In-process contracts** (devmode analog): a `Contract` subclass is
  registered with the runtime and invoked directly against the
  simulator — zero IPC, the mode benchmarks and tests use.
* **Chaincode-as-a-service** (ccaas analog): the contract runs in its
  own process hosting an RPC server; the peer calls ``Invoke`` and the
  chaincode calls back state ops over the same stream, mirroring the
  handler FSM message loop (see fabric_tpu/peer/ccaas.py).

Either way the runtime owns namespace scoping: a contract only touches
its own namespace unless it explicitly invokes another chaincode
(InvokeChaincode semantics — same-channel read-write)."""

from __future__ import annotations

import json
from dataclasses import dataclass


class ChaincodeError(Exception):
    pass


@dataclass
class Response:
    status: int = 200
    payload: bytes = b""
    message: str = ""


class ContractStub:
    """The API a contract sees (shim/stub analog), bound to one
    (simulator, namespace, invocation)."""

    def __init__(self, runtime: "ChaincodeRuntime", sim, namespace: str,
                 args: list[bytes], transient: dict | None = None,
                 creator: bytes = b"", channel: str = ""):
        self._rt = runtime
        self._sim = sim
        self.namespace = namespace
        self.args = args
        self.transient = transient or {}
        self.creator = creator
        self.channel = channel
        self.events: list[tuple[str, bytes]] = []

    # state ---------------------------------------------------------------
    def get_state(self, key: str) -> bytes | None:
        return self._sim.get_state(self.namespace, key)

    def put_state(self, key: str, value: bytes) -> None:
        self._sim.set_state(self.namespace, key, value)

    def del_state(self, key: str) -> None:
        self._sim.delete_state(self.namespace, key)

    def get_state_range(self, start: str, end: str, limit: int = 0):
        return self._sim.get_state_range(self.namespace, start, end, limit)

    def set_state_validation_parameter(self, key: str,
                                       policy_bytes: bytes) -> None:
        """Key-level endorsement policy (shim
        SetStateValidationParameter): a serialized
        SignaturePolicyEnvelope that the commit-path SBE pass enforces
        for every later write to ``key``."""
        self._sim.set_state_validation_parameter(
            self.namespace, key, policy_bytes
        )

    def get_state_validation_parameter(self, key: str) -> bytes | None:
        return self._sim.get_state_validation_parameter(self.namespace, key)

    def set_state_metadata(self, key: str, metadata: dict) -> None:
        self._sim.set_state_metadata(self.namespace, key, metadata)

    def get_private(self, coll: str, key: str) -> bytes | None:
        return self._sim.get_private_data(self.namespace, coll, key)

    def put_private(self, coll: str, key: str, value: bytes) -> None:
        self._sim.set_private_data(self.namespace, coll, key, value)

    # events / cross-chaincode --------------------------------------------
    def set_event(self, name: str, payload: bytes) -> None:
        self.events.append((name, payload))

    def invoke_chaincode(self, chaincode: str, args: list[bytes]) -> Response:
        """Same-channel chaincode-to-chaincode call: the callee builds
        its rwset into the SAME simulator under its own namespace
        (handler.go HandleInvokeChaincode semantics)."""
        return self._rt.execute(self._sim, chaincode, args,
                                transient=self.transient,
                                creator=self.creator, channel=self.channel)


class Contract:
    """Subclass and register: dispatches args[0] as the method name."""

    def invoke(self, stub: ContractStub) -> Response:
        if not stub.args:
            return Response(400, message="no function")
        fn_name = stub.args[0].decode()
        # only subclass-defined public methods are invocable — base
        # machinery (invoke itself) would recurse unboundedly
        if fn_name.startswith("_") or hasattr(Contract, fn_name):
            return Response(400, message=f"unknown function {fn_name}")
        fn = getattr(self, fn_name, None)
        if not callable(fn):
            return Response(400, message=f"unknown function {fn_name}")
        try:
            out = fn(stub, *stub.args[1:])
        except ChaincodeError as e:
            return Response(500, message=str(e))
        if isinstance(out, Response):
            return out
        return Response(200, payload=out if isinstance(out, bytes) else b"")


class ChaincodeRuntime:
    """namespace → executable contract (the ChaincodeSupport registry
    analog; launchers register in-process or ccaas-backed handlers)."""

    def __init__(self, resolver=None):
        self._contracts: dict[str, object] = {}
        # resolver(name, channel) → Contract | None: called on a
        # registry miss — the peer binds it to the lifecycle install
        # store so a COMMITTED definition whose approved package is
        # installed launches without manual registration (the
        # reference's lifecycle → external-builder launch path).
        # Resolutions cache PER (channel, name) — the same name on two
        # channels may bind different packages — and are dropped when
        # a committed block writes the lifecycle namespace (upgrades
        # must rebind).
        self.resolver = resolver
        self._resolved: dict[tuple, object] = {}

    def register(self, name: str, contract) -> None:
        self._contracts[name] = contract

    def registered(self, name: str) -> bool:
        return name in self._contracts

    def invalidate_resolved(self) -> None:
        """Lifecycle state changed (commit/upgrade): re-resolve on the
        next invoke instead of serving a stale endpoint."""
        self._resolved.clear()

    def execute(self, sim, name: str, args: list[bytes],
                transient: dict | None = None, creator: bytes = b"",
                channel: str = "") -> Response:
        contract = self._contracts.get(name)
        if contract is None:
            contract = self._resolved.get((channel, name))
        if contract is None and self.resolver is not None:
            contract = self.resolver(name, channel)
            if contract is not None:
                self._resolved[(channel, name)] = contract
        if contract is None:
            raise ChaincodeError(f"chaincode {name} not installed")
        stub = ContractStub(self, sim, name, args, transient, creator,
                            channel=channel)
        resp = contract.invoke(stub)
        resp.events = stub.events  # type: ignore[attr-defined]
        return resp


# ---------------------------------------------------------------------------
# sample contracts (integration/chaincode analogs, used by tests/bench)


class KVContract(Contract):
    """simple key-value chaincode (integration/chaincode/simple)."""

    def put(self, stub, key: bytes, value: bytes):
        stub.put_state(key.decode(), value)
        return b"ok"

    def get(self, stub, key: bytes):
        v = stub.get_state(key.decode())
        if v is None:
            return Response(404, message="not found")
        return v

    def delete(self, stub, key: bytes):
        stub.del_state(key.decode())
        return b"ok"

    def transfer(self, stub, frm: bytes, to: bytes, amount: bytes):
        if frm == to:
            return Response(400, message="self-transfer")
        a = int(stub.get_state(frm.decode()) or b"0")
        b = int(stub.get_state(to.decode()) or b"0")
        amt = int(amount)
        if a < amt:
            return Response(500, message="insufficient funds")
        stub.put_state(frm.decode(), str(a - amt).encode())
        stub.put_state(to.decode(), str(b + amt).encode())
        return b"ok"

    def range_sum(self, stub, start: bytes, end: bytes):
        total = sum(
            int(v) for _, v in stub.get_state_range(start.decode(), end.decode())
        )
        return str(total).encode()

    def put_private(self, stub, coll: bytes, key: bytes):
        value = stub.transient.get("value")
        if value is None:
            return Response(400, message="missing transient value")
        stub.put_private(coll.decode(), key.decode(), value)
        return b"ok"


class MarblesContract(Contract):
    """JSON-document chaincode exercising rich state (statecouchdb
    analog paths: execute_query over JSON values)."""

    def create(self, stub, name: bytes, color: bytes, size: bytes, owner: bytes):
        doc = {"docType": "marble", "name": name.decode(),
               "color": color.decode(), "size": int(size), "owner": owner.decode()}
        stub.put_state(name.decode(), json.dumps(doc).encode())
        stub.set_event("marble_created", name)
        return b"ok"

    def transfer(self, stub, name: bytes, new_owner: bytes):
        raw = stub.get_state(name.decode())
        if raw is None:
            return Response(404, message="no such marble")
        doc = json.loads(raw)
        doc["owner"] = new_owner.decode()
        stub.put_state(name.decode(), json.dumps(doc).encode())
        return b"ok"


class LayeredRuntime(ChaincodeRuntime):
    """Per-channel view over a shared runtime: system chaincodes
    (``_lifecycle`` with the channel's org set, qscc-style helpers)
    resolve first, user chaincodes fall through to the node-wide
    registry (the reference's system-chaincode deploy loop,
    internal/peer/node/start.go:765)."""

    def __init__(self, base: ChaincodeRuntime, overlays: dict | None = None):
        super().__init__()
        self._base = base
        self._contracts.update(overlays or {})

    def registered(self, name: str) -> bool:
        return name in self._contracts or self._base.registered(name)

    def execute(self, sim, name: str, args, transient=None, creator=b"",
                channel: str = ""):
        if name in self._contracts:
            contract = self._contracts[name]
            stub = ContractStub(self, sim, name, args, transient, creator,
                                channel=channel)
            resp = contract.invoke(stub)
            resp.events = stub.events  # type: ignore[attr-defined]
            return resp
        return self._base.execute(sim, name, args, transient=transient,
                                  creator=creator, channel=channel)

"""Gateway service: the v2.4+ single-endpoint transaction API.

Reference: internal/pkg/gateway — Evaluate (endorse.go sibling,
evaluate.go:23), Endorse (endorse.go:170, returns a PREPARED
transaction for the client to sign — the gateway never holds client
keys), Submit (submit.go:31, orderer broadcast incl. retry over the
orderer set), CommitStatus (commitstatus.go:26, ledger commit
notifications), ChaincodeEvents (event stream from committed blocks).

The endorsement plan comes from the discovery layouts
(fabric_tpu.discovery.layouts_for_policy ==
discovery/endorsement/endorsement.go:84 PeersForEndorsement); per-org
peers come from the node's PeerRegistry.
"""

from __future__ import annotations

import asyncio
import json
import logging

from fabric_tpu import protoutil
from fabric_tpu.comm.rpc import RpcClient
from fabric_tpu.discovery import DiscoveryService, layouts_for_policy
from fabric_tpu.peer import txassembly as txa

from fabric_tpu.observe import txflow as _txflow
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.protos import common_pb2, proposal_pb2, transaction_pb2

_log = logging.getLogger("fabric_tpu.gateway")


class GatewayError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status


def _envelope_tx_id(env_bytes: bytes) -> str:
    """tx_id from a signed Envelope's channel header, for the
    tx-flow submit/broadcast stamps — contained: an unparsable
    envelope is the orderer's problem to reject, not the journal's."""
    try:
        env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
        payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
        ch = protoutil.unmarshal(
            common_pb2.ChannelHeader, payload.header.channel_header
        )
        return ch.tx_id
    except Exception:
        return ""


class Gateway:
    """Bound to one PeerNode; registered on its RPC server."""

    def __init__(self, node):
        self.node = node

    # -- helpers -----------------------------------------------------------

    def _parse_proposal(self, req: bytes):
        signed = proposal_pb2.SignedProposal()
        signed.ParseFromString(req)
        prop = protoutil.unmarshal(proposal_pb2.Proposal, signed.proposal_bytes)
        header = protoutil.unmarshal(common_pb2.Header, prop.header)
        ch = protoutil.unmarshal(common_pb2.ChannelHeader, header.channel_header)
        ext = protoutil.unmarshal(
            proposal_pb2.ChaincodeHeaderExtension, ch.extension
        )
        chan = self.node.channels.get(ch.channel_id)
        if chan is None:
            raise GatewayError(404, f"not joined to {ch.channel_id}")
        return signed, prop, ch, ext.chaincode_id.name, chan

    async def _endorse_local(self, chan, signed):
        # endorse_signer: the node's batched ESCC sign provider when
        # sign_device armed one (peer/signlane) — concurrent client
        # streams then fill device sign lanes; the serial signer
        # otherwise (bit-equal signatures either way, RFC 6979)
        endorser = chan.make_endorser(
            self.node.msp,
            getattr(self.node, "endorse_signer", None) or self.node.signer,
            self.node.runtime,
        )
        loop = asyncio.get_event_loop()
        async with chan.commit_lock.reader():
            return await loop.run_in_executor(
                None, endorser.process_proposal, signed
            )

    async def _endorse_remote(self, host, port, req: bytes):
        """One remote Endorse RPC; transport/parse failures surface as
        a retryable GatewayError(503) so the layout loop fails over to
        the next layout instead of tearing the whole Endorse down."""
        try:
            cli = RpcClient(
                host, port,
                ssl_ctx=self.node.tls.client_ctx()
                if getattr(self.node, "tls", None) else None,
            )
            await cli.connect()
            try:
                raw = await cli.unary("Endorse", req)
            finally:
                await cli.close()
            pr = proposal_pb2.ProposalResponse()
            pr.ParseFromString(raw)
            return pr
        except GatewayError:
            raise
        except Exception as e:
            raise GatewayError(
                503, f"remote endorse {host}:{port} failed: {e}"
            ) from e

    # -- service methods ---------------------------------------------------

    async def evaluate(self, req: bytes) -> bytes:
        """Run the proposal on THIS peer; return the chaincode Response
        (no ordering) — read-only queries."""
        signed, _, _, _, chan = self._parse_proposal(req)
        result = await self._endorse_local(chan, signed)
        pr = result.response
        if pr.response.status >= 400 or not pr.payload:
            return pr.response.SerializeToString()
        # the chaincode's Response lives inside prp.extension
        prp = protoutil.unmarshal(
            proposal_pb2.ProposalResponsePayload, pr.payload
        )
        cca = protoutil.unmarshal(proposal_pb2.ChaincodeAction, prp.extension)
        return cca.response.SerializeToString()

    async def endorse(self, req: bytes) -> bytes:
        """Collect endorsements per the discovery layout; return the
        PREPARED transaction payload for the client to sign.

        Endorsement failures (simulation errors, a 429 from a full
        sign batcher, remote transport failures wrapped as 503) fail
        the CURRENT layout and the loop tries the next one; when no
        layout survives, the last error propagates — a 429 tells the
        client to back off briefly and retry, a 503 to try another
        gateway peer."""
        signed, prop, ch, cc_name, chan = self._parse_proposal(req)
        # tx-flow journal: the endorse stage opens the per-tx record
        # (observe/txflow.py) — a failed endorsement terminates the
        # flow, a prepared one waits for submit/inclusion
        _txflow.endorse_begin(ch.tx_id)
        try:
            payload = await self._endorse_inner(
                req, signed, prop, ch, cc_name, chan
            )
        except BaseException:
            _txflow.endorse_end(ch.tx_id, ok=False)
            raise
        _txflow.endorse_end(ch.tx_id)
        return payload

    async def _endorse_inner(self, req, signed, prop, ch, cc_name,
                             chan) -> bytes:
        info = chan.validator.policies.info(cc_name)
        if info is None:
            raise GatewayError(404, f"no validation info for {cc_name}")
        layouts = layouts_for_policy(info.policy)
        my_org = self.node.signer.msp_id
        responses = []
        last_err = None
        local_res = None  # simulate locally ONCE across layout attempts
        for layout in sorted(
            layouts, key=lambda l: (my_org not in l, sum(l.values()))
        ):
            try:
                responses = []
                for org, count in sorted(layout.items()):
                    if org == my_org:
                        if local_res is None:
                            local_res = await self._endorse_local(chan, signed)
                        res = local_res
                        if res.response.response.status >= 400:
                            raise GatewayError(
                                res.response.response.status,
                                res.response.response.message,
                            )
                        responses.append(res.response)
                        count -= 1
                    peers = self.node.registry.for_org(org)
                    if count > len(peers):
                        raise GatewayError(
                            503, f"not enough peers for {org}"
                        )
                    for p in peers[:count]:
                        pr = await self._endorse_remote(p.host, p.port, req)
                        if pr.response.status >= 400:
                            raise GatewayError(pr.response.status, pr.response.message)
                        responses.append(pr)
                break
            except GatewayError as e:
                last_err = e
                responses = []
        if not responses:
            raise last_err or GatewayError(503, "no viable endorsement layout")
        payload = txa.prepare_transaction(prop, responses)
        return payload.SerializeToString()

    async def submit(self, req: bytes) -> bytes:
        """req: JSON{channel} ‖ 0x00 ‖ signed Envelope bytes → orderer
        broadcast with failover across the channel's orderer set."""
        hdr, env_bytes = req.split(b"\x00", 1)
        channel = json.loads(hdr)["channel"]
        chan = self.node.channels.get(channel)
        if chan is None:
            raise GatewayError(404, f"not joined to {channel}")
        addrs = getattr(chan, "orderer_addrs", None) or []
        if not addrs:
            raise GatewayError(503, "no orderers known for channel")
        # tx-flow journal: the envelope parse to recover tx_id is only
        # paid when the journal is armed (one global check disarmed)
        tx_id = _envelope_tx_id(env_bytes) if _txflow.enabled() else ""
        if tx_id:
            _txflow.submit_begin(tx_id)
        from fabric_tpu.ordering.node import BroadcastClient

        cli = BroadcastClient(
            list(addrs),
            ssl_ctx=self.node.tls.client_ctx()
            if getattr(self.node, "tls", None) else None,
        )
        try:
            res = await cli.broadcast(channel, env_bytes)
        finally:
            await cli.close()
        if res.get("status") != 200:
            raise GatewayError(res.get("status", 500), res.get("info", "broadcast failed"))
        if tx_id:
            _txflow.broadcast_done(tx_id)
        return json.dumps({"status": 200}).encode()

    async def commit_status(self, req: bytes) -> bytes:
        """req: JSON{channel, tx_id, timeout?} → {code, block} once the
        tx commits (ledger commit notification analog).

        The answer lands as soon as the tx is IN a block, but under
        the decoupled committer (ledger/committer.py) its writes may
        not be state-visible yet — ``applied`` is the honest
        read-your-writes bit (true iff state apply has passed the
        tx's block), alongside the channel's ``durable_height``
        (appends past the fsync fence) and ``applied_height``."""
        q = json.loads(req)
        chan = self.node.channels.get(q["channel"])
        if chan is None:
            raise GatewayError(404, f"not joined to {q['channel']}")
        deadline = asyncio.get_event_loop().time() + float(q.get("timeout", 30.0))
        txid = q["tx_id"]
        while True:
            loc = chan.ledger.blocks.get_tx_loc(txid)
            if loc is not None:
                num, txnum, code = loc
                ledger = chan.ledger
                eng = getattr(ledger, "engine", None)
                if eng is not None:
                    applied_height = (
                        int(eng.stats().get("applied_num", -1)) + 1
                    )
                else:
                    # serial commit: state apply completes inside
                    # commit_block, so applied tracks block height
                    applied_height = int(ledger.blocks.height)
                durable_height = int(
                    getattr(ledger.blocks, "synced_height",
                            ledger.blocks.height)
                )
                return json.dumps(
                    {"tx_id": txid, "code": int(code), "block": int(num),
                     "code_name": transaction_pb2.TxValidationCode.Name(int(code)),
                     "applied": applied_height > int(num),
                     "applied_height": applied_height,
                     "durable_height": durable_height}
                ).encode()
            remaining = deadline - asyncio.get_event_loop().time()
            if remaining <= 0:
                raise GatewayError(408, f"timeout waiting for {txid}")
            ev = chan._height_changed
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                raise GatewayError(408, f"timeout waiting for {txid}")

    async def chaincode_events(self, stream):
        """stream request: JSON{channel, chaincode, start?} → one JSON
        event per message from committed VALID txs."""
        req = json.loads(await stream.__anext__())
        chan = self.node.channels.get(req["channel"])
        if chan is None:
            await stream.error("no such channel")
            return
        want_cc = req["chaincode"]
        num = int(req.get("start", 0))
        while True:
            if num >= chan.height:
                await chan._height_changed.wait()
                continue
            blk = chan.ledger.blocks.get_block(num)
            if blk is None:
                await stream.error(
                    f"block {num} unavailable (pre-snapshot)"
                )
                return
            flags = protoutil.get_tx_filter(blk)
            for i, env_bytes in enumerate(blk.data.data):
                if i < len(flags) and flags[i] != 0:
                    continue
                try:
                    env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
                    _, _, cap, prp, cca = protoutil.extract_action(env)
                except Exception as e:
                    _log.debug(
                        "event stream: tx %d of block %d not an "
                        "endorser action: %s", i, blk.header.number, e,
                    )
                    continue
                if not cca.events:
                    continue
                ev = protoutil.unmarshal(proposal_pb2.ChaincodeEvent, cca.events)
                if ev.chaincode_id != want_cc:
                    continue
                await stream.send(json.dumps({
                    "block": num, "tx_id": ev.tx_id,
                    "event_name": ev.event_name,
                    "payload": ev.payload.hex(),
                }).encode())
            num += 1


def register(node) -> Gateway:
    """Attach gateway services to a PeerNode's RPC server.

    Unary responses are framed: 0x00 ‖ payload on success,
    0x01 ‖ JSON{status, error} on failure."""
    gw = Gateway(node)

    def unary(fn):
        async def handler(req: bytes) -> bytes:
            try:
                return b"\x00" + await fn(req)
            except GatewayError as e:
                return b"\x01" + json.dumps(
                    {"error": str(e), "status": e.status}
                ).encode()
        return handler

    node.server.register_unary("GwEvaluate", unary(gw.evaluate))
    node.server.register_unary("GwEndorse", unary(gw.endorse))
    node.server.register_unary("GwSubmit", unary(gw.submit))
    node.server.register_unary("GwCommitStatus", unary(gw.commit_status))
    node.server.register("GwChaincodeEvents", gw.chaincode_events)
    return gw


class GatewayClient:
    """SDK-side convenience over the gateway surface (the
    fabric-gateway client analog): sign → endorse → sign → submit →
    await commit."""

    def __init__(self, host: str, port: int, signer, ssl_ctx=None):
        self.host, self.port = host, port
        self.signer = signer
        self.ssl_ctx = ssl_ctx
        self._cli: RpcClient | None = None

    async def _client(self) -> RpcClient:
        if self._cli is None:
            self._cli = RpcClient(self.host, self.port, ssl_ctx=self.ssl_ctx)
            await self._cli.connect()
        return self._cli

    async def close(self):
        if self._cli is not None:
            await self._cli.close()

    @staticmethod
    def _unwrap(raw: bytes) -> bytes:
        if raw[:1] == b"\x01":
            err = json.loads(raw[1:])
            raise GatewayError(err.get("status", 500), err.get("error", ""))
        return raw[1:]

    async def evaluate(self, channel: str, chaincode: str, args: list[bytes]):
        signed, _, _ = txa.create_signed_proposal(
            self.signer, channel, chaincode, args
        )
        cli = await self._client()
        raw = self._unwrap(await cli.unary(
            "GwEvaluate", signed.SerializeToString(), timeout=120.0
        ))
        resp = proposal_pb2.Response()
        resp.ParseFromString(raw)
        return resp

    async def submit_transaction(self, channel: str, chaincode: str,
                                 args: list[bytes], wait: bool = True,
                                 transient: dict | None = None):
        """The full gateway round trip; returns (tx_id, status dict)."""
        signed, tx_id, _ = txa.create_signed_proposal(
            self.signer, channel, chaincode, args, transient=transient
        )
        cli = await self._client()
        payload_bytes = self._unwrap(
            await cli.unary(
                "GwEndorse", signed.SerializeToString(), timeout=120.0
            )
        )
        env = common_pb2.Envelope(
            payload=payload_bytes, signature=self.signer.sign(payload_bytes)
        )
        hdr = json.dumps({"channel": channel}).encode()
        self._unwrap(await cli.unary(
            "GwSubmit", hdr + b"\x00" + env.SerializeToString(), timeout=60.0
        ))
        if not wait:
            return tx_id, None
        raw = self._unwrap(await cli.unary(
            "GwCommitStatus",
            json.dumps({"channel": channel, "tx_id": tx_id,
                        "timeout": 120.0}).encode(),
            timeout=130.0,
        ))
        return tx_id, json.loads(raw)

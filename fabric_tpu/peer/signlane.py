"""Sign-batch ingest: coalescing concurrent ESCC sign requests into
device sign batches — the gateway-side twin of the sidecar's verify
coalescing.

Every proposal the endorser simulates ends in ONE ECDSA signature over
``prp_bytes ‖ endorser`` (txassembly.create_proposal_response).  With
concurrent gateway clients those signatures arrive as a stream of
independent 1-item requests; the device lane (ops/p256sign) only pays
off when they dispatch as one padded batch.  The :class:`SignBatcher`
sits between them:

* endorser threads call :meth:`SignBatcher.sign` (blocking, like the
  serial ``SigningIdentity.sign`` call it replaces),
* a flusher thread drains up to ``batch_max`` pending digests per
  flush, waiting at most ``wait_ms`` after the first arrival (the
  max-batch / max-wait coalescing contract the sidecar dispatcher
  uses),
* a full admission queue answers a typed :class:`SignBusy` instead of
  buffering unboundedly — the endorser maps it to a 429 proposal
  response and the gateway to a retryable ``GatewayError`` (the
  scheduler/BUSY pattern from the sidecar, PR 7–8),
* per-batch occupancy/wait/backend-time histograms plus a
  :meth:`stats` snapshot feed the bench extras and the autopilot's
  ``sign_batch_max`` knob.

Nonces are RFC 6979 (``crypto/ec_ref``) in BOTH backends, so batched
device signing and the serial CPU path produce BIT-EQUAL signatures —
the concurrency differential (N async clients ≡ N serial endorsements)
is pinned by tests/test_signlane.py.

Module-level imports are stdlib + pure-Python crypto only; the device
backend imports jax lazily, so CPU-only hosts constructing a serial
batcher never touch it.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from collections import deque

from fabric_tpu.crypto import ec_ref
from fabric_tpu.utils.stats import nearest_rank

_log = logging.getLogger("fabric_tpu.signlane")

#: retry hint a BUSY answer carries (ms) — longer than the sidecar's
#: queue-full 20 ms: a full sign queue means a whole batch must flush
#: first, which includes a device round trip
SIGN_RETRY_MS = 50

#: admission bound, in batches: one batch signing on device + one
#: accumulating behind it.  Beyond that, buffering only grows latency
#: — answer BUSY and let the client retry against a drained queue.
_QUEUE_BATCHES = 2

#: seconds the busy-rate / wait-percentile windows look back.  The
#: signals are TIME-windowed, not count-windowed: a burst of BUSY
#: bounces followed by silence must DECAY (an idle lane reads
#: busy_rate 0.0 and wait n=0), or the autopilot would keep
#: ratcheting ``sign_batch_max`` up on a dead lane off a frozen
#: trailing count.
_SIGNAL_WINDOW_S = 30.0


class SignBusy(Exception):
    """Typed overflow answer from a full sign batcher."""

    def __init__(self, depth: int, cap: int,
                 retry_ms: int = SIGN_RETRY_MS):
        super().__init__(
            f"sign batcher full ({depth}/{cap} pending); "
            f"retry in {retry_ms} ms"
        )
        self.depth = depth
        self.cap = cap
        self.retry_ms = retry_ms


class _Pending:
    __slots__ = ("digest", "event", "result", "error", "t_submit")

    def __init__(self, digest: int, t_submit: float):
        self.digest = digest
        self.event = threading.Event()
        self.result: tuple[int, int] | None = None
        self.error: BaseException | None = None
        self.t_submit = t_submit


def _metrics(registry):
    if registry is None:
        from fabric_tpu.ops_metrics import global_registry

        registry = global_registry()
    return (
        registry.histogram(
            "sign_batch_lanes",
            "sign requests coalesced per batch flush",
            buckets=(1, 4, 16, 64, 256, 1024, float("inf")),
        ),
        registry.histogram(
            "sign_batch_wait_seconds",
            "submit → batch-dispatch wait per sign request (s)",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                     0.05, 0.1, float("inf")),
        ),
        registry.histogram(
            "sign_batch_backend_seconds",
            "backend sign time per batch flush (s)",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                     float("inf")),
        ),
        registry.counter(
            "sign_requests_total", "sign requests admitted"
        ),
        registry.counter(
            "sign_busy_total", "sign requests bounced with BUSY"
        ),
    )


class SignBatcher:
    """See module docstring.  ``sign_many``: the backend —
    ``list[digest_int] → list[(r, s)]`` (``device_sign_backend`` /
    ``cpu_sign_backend`` below, or any test double)."""

    def __init__(self, sign_many, batch_max: int = 256,
                 wait_ms: float = 2.0, registry=None,
                 clock=time.monotonic):
        if batch_max < 1:
            raise ValueError("batch_max must be >= 1")
        if wait_ms < 0:
            raise ValueError("wait_ms must be >= 0")
        self.sign_many = sign_many
        self.clock = clock
        self._cond = threading.Condition()
        self._pending: deque[_Pending] = deque()
        self._batch_max = int(batch_max)
        self._wait_ms = float(wait_ms)
        self._stopped = False
        self._thread: threading.Thread | None = None
        (self._lanes_h, self._wait_h, self._backend_h,
         self._req_ctr, self._busy_ctr) = _metrics(registry)
        # trailing-window admission record for stats()/autopilot:
        # (t, True = admitted | False = BUSY); bounded by count AND
        # aged out by _SIGNAL_WINDOW_S at read time
        self._recent: deque[tuple[float, bool]] = deque(maxlen=256)
        # per-request observer (observe/slo.endorse_observer shape):
        # called OUTSIDE the condition lock with (wait_ms, busy) —
        # flushed requests carry their coalescing-window wait, BUSY
        # bounces carry wait_ms=None.  Contained: an observer error
        # never kills the flusher or an endorser thread.
        self.observer = None
        self._wait_samples: deque[tuple[float, float]] = deque(
            maxlen=256
        )  # (t, wait ms)
        self._occupancy: deque[int] = deque(maxlen=64)
        self._signed_total = 0
        self._busy_total = 0
        self._batches_total = 0
        # flush sequence for the ns="sign" trace roots (flusher-thread
        # private — no lock needed)
        self._flush_seq = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "SignBatcher":
        if self._thread is None:
            # under the cond even though the flusher is not spawned
            # yet: a start() racing a stop()'s locked _stopped=True
            # must not interleave between its write and the join
            with self._cond:
                self._stopped = False
            self._thread = threading.Thread(
                target=self._run, name="fabtpu-signlane", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        t = self._thread
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
        # fail any stragglers loudly rather than stranding their waits
        with self._cond:
            while self._pending:
                p = self._pending.popleft()
                p.error = RuntimeError("sign batcher stopped")
                p.event.set()

    # -- runtime knobs (autopilot actuation) -------------------------------

    @property
    def batch_max(self) -> int:
        return self._batch_max

    def set_batch_max(self, n: int) -> None:
        """Latched under the condition lock; the flusher reads it at
        each drain, so the new cap applies from the next flush."""
        n = max(1, int(n))
        with self._cond:
            if n != self._batch_max:
                self._batch_max = n
                self._cond.notify_all()

    def set_wait_ms(self, ms: float) -> None:
        ms = max(0.0, float(ms))
        with self._cond:
            if ms != self._wait_ms:
                self._wait_ms = ms
                self._cond.notify_all()

    # -- the request side --------------------------------------------------

    def sign_digest(self, digest: int,
                    timeout_s: float = 120.0) -> tuple[int, int]:
        """Block until the batch carrying ``digest`` flushes; →
        (r, s).  Raises :class:`SignBusy` on admission overflow."""
        now = self.clock()
        busy_exc = None
        with self._cond:
            cap = self._batch_max * _QUEUE_BATCHES
            if self._stopped:
                raise RuntimeError("sign batcher stopped")
            if len(self._pending) >= cap:
                self._busy_total += 1
                self._recent.append((now, False))
                self._busy_ctr.add()
                busy_exc = SignBusy(len(self._pending), cap)
            else:
                p = _Pending(int(digest), now)
                self._pending.append(p)
                self._recent.append((now, True))
                self._req_ctr.add()
                self._cond.notify_all()
        if busy_exc is not None:
            # outside the lock: the endorse SLO feed must never
            # serialize (or deadlock) the admission path
            self._observe(None, True)
            raise busy_exc
        deadline = time.monotonic() + timeout_s
        warn_at = time.monotonic() + 60.0
        while not p.event.wait(timeout=1.0):
            now_m = time.monotonic()
            if now_m >= deadline:
                raise TimeoutError("sign batch never flushed")
            if now_m >= warn_at:
                _log.warning("sign request waiting > 60s on batcher")
                warn_at = now_m + 60.0
        if p.error is not None:
            raise p.error
        assert p.result is not None
        return p.result

    def sign(self, message: bytes) -> bytes:
        """The drop-in ``SigningIdentity.sign`` form: SHA-256 the
        message, batch-sign, return the DER-encoded low-S (r, s)."""
        e = int.from_bytes(hashlib.sha256(message).digest(), "big")
        r, s = self.sign_digest(e)
        return ec_ref.der_encode_sig(r, s)

    # -- the flusher -------------------------------------------------------

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            self._flush(batch)

    def _collect(self) -> list[_Pending] | None:
        """Wait for the first pending request, then linger up to
        ``wait_ms`` (or until ``batch_max`` fills) before draining —
        the max-batch / max-wait coalescing window."""
        with self._cond:
            while not self._pending and not self._stopped:
                self._cond.wait(timeout=0.5)
            if self._stopped:
                return None
            first = self._pending[0].t_submit
            deadline = first + self._wait_ms / 1000.0
            while (len(self._pending) < self._batch_max
                   and not self._stopped):
                remaining = deadline - self.clock()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=min(remaining, 0.05))
            k = min(len(self._pending), self._batch_max)
            return [self._pending.popleft() for _ in range(k)]

    def _flush(self, batch: list[_Pending]) -> None:
        t0 = self.clock()
        with self._cond:
            # recorded under the lock: stats() iterates these deques
            # while holding it, and a lock-free append from here would
            # raise "deque mutated during iteration" mid-snapshot
            for p in batch:
                self._wait_samples.append(
                    (t0, max(0.0, (t0 - p.t_submit) * 1000.0))
                )
            self._occupancy.append(len(batch))
        for p in batch:
            w = max(0.0, t0 - p.t_submit)
            self._wait_h.observe(w)
            self._observe(w * 1000.0, False)
        self._lanes_h.observe(len(batch))
        # one trace root per flush in the "sign" flight-recorder ring:
        # the device ledger's dev:* child spans (and its histogram
        # exemplars) need a tree to attach to on the flusher thread,
        # and /trace?ns=sign gets the sign lane's own waterfall.  A
        # disabled tracer makes every call below a no-op.
        from fabric_tpu.observe import global_tracer

        tr = global_tracer()
        self._flush_seq += 1
        root = tr.begin_block(self._flush_seq, ns="sign",
                              lanes=len(batch))
        tok = tr.attach(root) if root is not None else None
        try:
            sigs = self.sign_many([p.digest for p in batch])
            if len(sigs) != len(batch):
                raise RuntimeError(
                    f"sign backend returned {len(sigs)} signatures "
                    f"for {len(batch)} digests"
                )
        except BaseException as e:  # the waiters get the real error
            for p in batch:
                p.error = e
                p.event.set()
            return
        finally:
            if root is not None:
                tr.detach(tok)
                tr.finish_block(root)
        self._backend_h.observe(self.clock() - t0)
        with self._cond:
            self._batches_total += 1
            self._signed_total += len(batch)
        for p, rs in zip(batch, sigs):
            p.result = rs
            p.event.set()

    # -- observability -----------------------------------------------------

    def _observe(self, wait_ms, busy: bool) -> None:
        """Hand one request event to the attached observer (the
        endorse-side SLO feed, observe/slo.endorse_observer) —
        contained, lock-free."""
        obs = self.observer
        if obs is None:
            return
        try:
            obs(wait_ms, busy)
        except Exception as e:
            _log.debug("sign-lane observer failed: %s", e)

    def stats(self) -> dict:
        """Snapshot for bench extras and the autopilot's sign knob:
        trailing busy rate, wait percentiles, batch occupancy."""
        now = self.clock()
        horizon = now - _SIGNAL_WINDOW_S
        with self._cond:
            recent = [ok for t, ok in self._recent if t >= horizon]
            waits = sorted(w for t, w in self._wait_samples
                           if t >= horizon)
            occ = sorted(self._occupancy)
            depth = len(self._pending)
            out = {
                "depth": depth,
                "cap": self._batch_max * _QUEUE_BATCHES,
                "batch_max": self._batch_max,
                "wait_ms_knob": self._wait_ms,
                "signed_total": self._signed_total,
                "busy_total": self._busy_total,
                "batches_total": self._batches_total,
            }
        out["busy_rate"] = (
            recent.count(False) / len(recent) if recent else 0.0
        )
        # nearest-rank, the SAME convention as the sidecar scheduler's
        # queue ages — two stats surfaces feeding one autopilot must
        # not disagree on what "p99" means
        pct = lambda vals, q: nearest_rank(vals, q) if vals else None
        out["wait_ms"] = {
            "n": len(waits), "p50": pct(waits, 50), "p99": pct(waits, 99),
        }
        out["occupancy"] = {
            "n": len(occ), "p50": pct(occ, 50),
            "max": occ[-1] if occ else None,
        }
        return out


# ---------------------------------------------------------------------------
# Backends and the provider wrapper


def private_scalar(signer) -> int:
    """Extract the raw P-256 private scalar d from a signer: an
    ``ec_ref.SigningKey`` (``.d``), an ``identity.SigningIdentity``
    (``.key.private_numbers().private_value``), or anything exposing
    either shape."""
    d = getattr(signer, "d", None)
    if isinstance(d, int):
        return d
    key = getattr(signer, "key", None)
    if key is not None:
        pn = getattr(key, "private_numbers", None)
        if pn is not None:
            return int(pn().private_value)
    raise ValueError(
        f"cannot extract a P-256 private scalar from {type(signer).__name__}"
    )


def cpu_sign_backend(d: int):
    """Serial RFC 6979 signing over `ec_ref` — the bit-equal oracle
    backend (no jax import; pure Python)."""
    key = ec_ref.SigningKey(int(d))
    return lambda digests: [key.sign_digest(int(e)) for e in digests]


def device_sign_backend(d: int, chunk: int = 0, mesh_devices: int = 0,
                        verify_after: bool = False):
    """Batched device signing via ops/p256sign — jax imported lazily
    so merely constructing a CPU batcher never pulls the device
    stack.  ``chunk``/``mesh_devices`` compose like the verify lane's
    knobs; ``verify_after`` arms the self-check lane (each batch
    re-verified on device before release)."""
    d = int(d)
    mesh_holder: list = [None, False]

    def sign_many(digests):
        from fabric_tpu.ops import p256sign

        if mesh_devices and not mesh_holder[1]:
            from fabric_tpu.parallel.mesh import resolve_mesh

            mesh_holder[0] = resolve_mesh(mesh_devices)
            mesh_holder[1] = True
        return p256sign.sign_digests(
            digests, d, chunk=chunk or None, mesh=mesh_holder[0],
            verify_after=verify_after,
        )

    return sign_many


class BatchedSigner:
    """The provider the Endorser consumes in place of its direct
    signer: ``.sign`` routes through the batcher, everything else
    (``serialized``, ``msp_id``, ``cert_pem``, ...) delegates to the
    wrapped base signer — so ``txassembly.create_proposal_response``
    and the MSP plumbing see an ordinary signing identity."""

    def __init__(self, base, batcher: SignBatcher):
        self._base = base
        self.batcher = batcher

    def sign(self, message: bytes) -> bytes:
        return self.batcher.sign(message)

    def __getattr__(self, name):
        return getattr(self._base, name)

"""Device-lane degradation guard: bounded retry, CPU fallback latch,
recovery probing.

Before this module every failure on the device verify lane was
happy-path: a TPU launch raising tore the whole deliver stream down,
and the CPU ``ops/p256.verify_host`` path existed but nothing ever
routed to it.  :class:`DeviceLaneGuard` is the state machine that
makes the lane survivable, shared by ``BlockValidator`` and the
crypto-free toy validators the chaos tests drive:

* **bounded retry** — a failed device launch retries up to ``retries``
  times with capped exponential backoff + jitter
  (``utils.backoff.Backoff``), each retry counted on
  ``device_verify_retries_total``;
* **degraded latch** — after ``fail_threshold`` CONSECUTIVE failed
  attempts the guard latches degraded: blocks route to the caller's
  CPU fallback (``ops/p256.verify_host`` + the host MVCC path in the
  real validator — correctness identical, the channel stays live),
  counted on ``fallback_blocks_total``, with the
  ``validator_degraded`` gauge at 1 and the state surfaced on
  ``/healthz``;
* **recovery probe** — every ``recovery_s`` a degraded guard risks ONE
  block on the device lane; a completed device verify re-arms the lane
  (gauge back to 0).  A failed probe costs that block a CPU re-verify,
  nothing more;
* **deadline** — with ``deadline_ms`` > 0, a device attempt (launch,
  or the fetch-side sync the validator reports via
  :meth:`check_deadline`) that takes longer counts as a failure toward
  the latch.  The result is still USED — a blocked XLA sync cannot be
  preempted from Python — so the deadline is a latch signal for future
  blocks, not a per-block abort; that is the honest contract and it is
  documented on the knob.

Every device attempt passes through the ``validator.verify_launch``
fault-injection point (fabric_tpu.faults), so a seeded FaultPlan
exercises exactly this machinery; fallback runs under
``faults.shield()`` — the recovery path must not be chased by the
fault that provoked it.

``fail_threshold=0`` (the default everywhere) disables the guard
entirely: callers skip construction and keep today's raise-through
behavior, so CPU-only hosts and tier-1 pay nothing.
"""

from __future__ import annotations

import logging
import random
import threading
import time

from fabric_tpu import faults
from fabric_tpu.utils.backoff import Backoff

_log = logging.getLogger("fabric_tpu.validator.degrade")

LAUNCH_POINT = "validator.verify_launch"


class DeviceLaneGuard:
    """See module docstring.  The latch state is LOCKED: launches
    record failures on the prefetch thread while fetch-side accounting
    (``_GuardedHandle``, ``validate_finish``'s deadline/success path)
    runs on the caller thread — the counter/latch transitions must not
    race.  The lock guards only the few scalar updates, never the
    launch or fallback work itself."""

    def __init__(self, retries: int = 2, fail_threshold: int = 3,
                 recovery_s: float = 30.0, deadline_ms: float = 0.0,
                 backoff: Backoff | None = None, clock=time.monotonic,
                 sleep=time.sleep, channel: str = "", registry=None,
                 rng: random.Random | None = None):
        if fail_threshold <= 0:
            raise ValueError(
                "DeviceLaneGuard needs fail_threshold >= 1 "
                "(0 disables the guard — don't construct one)"
            )
        self.retries = max(0, int(retries))
        self.fail_threshold = int(fail_threshold)
        self.recovery_s = float(recovery_s)
        self.deadline_ms = float(deadline_ms)
        self.channel = channel
        self._clock = clock
        self._sleep = sleep
        self._backoff = backoff or Backoff(
            base=0.05, cap=2.0, jitter=0.5, rng=rng
        )
        self._lock = threading.Lock()
        self._consecutive = 0
        self._degraded = False
        self._degraded_at = 0.0
        self._degraded_accum_s = 0.0
        self._last_probe = 0.0
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self._gauge = registry.gauge(
            "validator_degraded",
            "1 while the device verify lane is latched to CPU fallback",
        )
        self._retries_ctr = registry.counter(
            "device_verify_retries_total",
            "device verify attempts retried after a failure",
        )
        self._fallback_ctr = registry.counter(
            "fallback_blocks_total",
            "blocks routed through the CPU verify fallback",
        )
        self._gauge.set(0, channel=self.channel)

    # -- state ------------------------------------------------------------

    @property
    def degraded(self) -> bool:
        return self._degraded

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive

    def degraded_seconds(self) -> float:
        """Total wall-clock spent degraded (bench chaos extras)."""
        with self._lock:
            live = (
                self._clock() - self._degraded_at if self._degraded
                else 0.0
            )
            return self._degraded_accum_s + live

    def record_failure(self, err: BaseException | None = None) -> None:
        with self._lock:
            self._consecutive += 1
            latched = (
                not self._degraded
                and self._consecutive >= self.fail_threshold
            )
            if latched:
                self._degraded = True
                self._degraded_at = self._clock()
                self._last_probe = self._degraded_at
                n = self._consecutive
        if latched:
            self._gauge.set(1, channel=self.channel)
            _log.warning(
                "%s: device verify lane DEGRADED after %d consecutive "
                "failures (%s) — routing blocks through the CPU "
                "fallback; recovery probe every %.1fs",
                self.channel or "validator", n, err, self.recovery_s,
            )
            # incident edge: the latch is exactly the moment the
            # flight-data recorder should freeze the trailing story
            # (import inside the rare branch — the unarmed fast path
            # never pays it)
            from fabric_tpu.observe import blackbox

            blackbox.notify(
                "degrade_latch", channel=self.channel,
                consecutive_failures=n,
                error=str(err) if err is not None else None,
            )

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._backoff.reset()
            rearmed = self._degraded
            if rearmed:
                now = self._clock()
                down_s = now - self._degraded_at
                self._degraded_accum_s += down_s
                self._degraded = False
        if rearmed:
            self._gauge.set(0, channel=self.channel)
            _log.warning(
                "%s: device verify lane RECOVERED after %.1fs degraded",
                self.channel or "validator", down_s,
            )

    def should_probe(self) -> bool:
        """Degraded and due for a device-lane attempt."""
        with self._lock:
            return (
                self._degraded
                and self._clock() - self._last_probe >= self.recovery_s
            )

    def check_deadline(self, elapsed_s: float) -> bool:
        """Report a device-side duration (launch or fetch sync).  Over
        the deadline it counts as a lane failure (latch signal); the
        caller still uses the result.  Returns True when the deadline
        was exceeded."""
        if self.deadline_ms > 0 and elapsed_s * 1000.0 > self.deadline_ms:
            _log.warning(
                "%s: device verify took %.1fms (deadline %.1fms) — "
                "counting toward the degraded latch",
                self.channel or "validator", elapsed_s * 1000.0,
                self.deadline_ms,
            )
            self.record_failure()
            return True
        return False

    # -- the launch wrapper ------------------------------------------------

    def run_launch(self, launch_fn, fallback_fn, eager: bool = False,
                   fallback_count: int = 1):
        """Run ``launch_fn`` on the device lane with bounded retries,
        or route to ``fallback_fn`` (the CPU path) when degraded /
        exhausted.

        ``eager=True``: ``launch_fn`` completes the verify synchronously
        (toy validators), so success is recorded on return.  With the
        default ``eager=False`` the launch is an ASYNC dispatch — the
        caller records success/failure when the device actually syncs
        (``record_success`` / ``record_failure`` at fetch).

        ``fallback_count``: blocks the fallback covers (a coalesced
        group routes several blocks through one CPU re-verify) — feeds
        ``fallback_blocks_total``.
        """
        if self._degraded:
            if not self.should_probe():
                return self._fallback(fallback_fn, fallback_count)
            # recovery probe: risk ONE attempt, no retries — a failure
            # costs this block a CPU re-verify, nothing more
            with self._lock:
                self._last_probe = self._clock()
            try:
                faults.fire(LAUNCH_POINT, probe=True)
                t0 = self._clock()
                out = launch_fn()
            except Exception as e:
                _log.info(
                    "%s: device recovery probe failed (%s); staying "
                    "degraded", self.channel or "validator", e,
                )
                return self._fallback(fallback_fn, fallback_count)
            if eager and not self.check_deadline(self._clock() - t0):
                self.record_success()
            return out

        attempts = self.retries + 1
        last_err: BaseException | None = None
        for i in range(attempts):
            try:
                faults.fire(LAUNCH_POINT)
                t0 = self._clock()
                out = launch_fn()
            except Exception as e:
                last_err = e
                self.record_failure(e)
                if self._degraded or i == attempts - 1:
                    break
                self._retries_ctr.add(1, channel=self.channel)
                self._sleep(self._backoff.next())
                continue
            if eager and not self.check_deadline(self._clock() - t0):
                self.record_success()
            return out
        _log.warning(
            "%s: device verify launch failed %d attempt(s) (%s) — "
            "routing this block through the CPU fallback",
            self.channel or "validator", self._consecutive, last_err,
        )
        return self._fallback(fallback_fn, fallback_count)

    def count_fallback(self, count: int = 1) -> None:
        """Count blocks that rode the CPU lane OUTSIDE ``run_launch``
        (fetch-side re-verifies) — ``fallback_blocks_total`` must
        cover every CPU-verified block, not just launch-time routing."""
        self._fallback_ctr.add(count, channel=self.channel)

    def _fallback(self, fallback_fn, count: int = 1):
        self._fallback_ctr.add(count, channel=self.channel)
        # the recovery path must not be chased by the injected fault
        # that provoked it (a real dead TPU does not break the CPU)
        with faults.shield():
            return fallback_fn()

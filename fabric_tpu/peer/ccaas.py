"""Chaincode-as-a-service: contracts running OUT-OF-PROCESS, speaking
a duplex state-callback stream with the peer.

Reference: the ccaas external builder (ccaas_builder/, ~0.9k LoC) plus
the chaincode handler FSM (core/chaincode/handler.go:364
ProcessStream): the chaincode registers/serves at an address, the peer
connects per invocation, and GetState/PutState/etc round-trip over the
stream while the transaction simulator accumulates the rwset
PEER-side.  Docker is deliberately not involved (the reference's own
direction for production deployments).

Wire format on the ``CCInvoke`` stream (JSON, values hex):
  peer → cc   {"chaincode", "args": [...], "transient": {...},
               "creator": "..."}
  cc  → peer  {"op": "get_state"|"put_state"|"del_state"|"get_range"|
               "get_private"|"put_private"|"set_event", ...}
  peer → cc   {"result": ...}
  cc  → peer  {"done": {"status", "payload", "message"}}
"""

from __future__ import annotations

import asyncio
import json
import threading

from fabric_tpu.comm.rpc import RpcClient, RpcServer
from fabric_tpu.peer.chaincode import Contract, Response


def _hx(b: bytes | None) -> str | None:
    return b.hex() if b is not None else None


def _unhx(s: str | None) -> bytes | None:
    return bytes.fromhex(s) if s is not None else None


# ---------------------------------------------------------------------------
# Chaincode-process side


class _RemoteStub:
    """The stub a ccaas contract sees: every state op round-trips to
    the peer over the stream (handler.go HandleGetState etc.)."""

    def __init__(self, loop, stream, invocation: dict):
        self._loop = loop
        self._stream = stream
        self.args = [bytes.fromhex(a) for a in invocation["args"]]
        self.transient = {
            k: bytes.fromhex(v) for k, v in invocation.get("transient", {}).items()
        }
        self.creator = bytes.fromhex(invocation.get("creator", ""))
        self.events: list = []

    def _roundtrip(self, msg: dict):
        async def go():
            await self._stream.send(json.dumps(msg).encode())
            reply = await self._stream.__anext__()
            return json.loads(reply)["result"]

        return asyncio.run_coroutine_threadsafe(go(), self._loop).result(30)

    def get_state(self, key: str):
        return _unhx(self._roundtrip({"op": "get_state", "key": key}))

    def put_state(self, key: str, value: bytes):
        self._roundtrip({"op": "put_state", "key": key, "value": _hx(value)})

    def del_state(self, key: str):
        self._roundtrip({"op": "del_state", "key": key})

    def get_state_range(self, start: str, end: str, limit: int = 0):
        rows = self._roundtrip({
            "op": "get_range", "start": start, "end": end, "limit": limit,
        })
        return [(k, _unhx(v)) for k, v in rows]

    def get_private(self, coll: str, key: str):
        return _unhx(self._roundtrip({
            "op": "get_private", "coll": coll, "key": key,
        }))

    def put_private(self, coll: str, key: str, value: bytes):
        self._roundtrip({
            "op": "put_private", "coll": coll, "key": key, "value": _hx(value),
        })

    def set_event(self, name: str, payload: bytes):
        self.events.append((name, payload))
        self._roundtrip({
            "op": "set_event", "name": name, "payload": _hx(payload),
        })


class ChaincodeServer:
    """Hosts contracts in the chaincode process (the ccaas server)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.server = RpcServer(host, port)
        self._contracts: dict[str, Contract] = {}
        self.port = port

    def register(self, name: str, contract: Contract) -> None:
        self._contracts[name] = contract

    async def start(self):
        self.server.register("CCInvoke", self._on_invoke)
        await self.server.start()
        self.port = self.server.port
        return self

    async def stop(self):
        await self.server.stop()

    async def _on_invoke(self, stream):
        inv = json.loads(await stream.__anext__())
        contract = self._contracts.get(inv["chaincode"])
        if contract is None:
            await stream.send(json.dumps({
                "done": {"status": 404,
                         "message": f"chaincode {inv['chaincode']} not served"}
            }).encode())
            return
        loop = asyncio.get_event_loop()
        stub = _RemoteStub(loop, stream, inv)
        resp = await loop.run_in_executor(None, contract.invoke, stub)
        await stream.send(json.dumps({
            "done": {"status": resp.status, "payload": _hx(resp.payload),
                     "message": resp.message}
        }).encode())


# ---------------------------------------------------------------------------
# Peer side: proxy contract forwarding to the ccaas server


class _CCaaSLoop:
    """One shared background event loop for all ccaas connections —
    peer-side chaincode execution happens in executor threads, so the
    RPC round trips need a loop of their own."""

    _instance = None

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=self.loop.run_forever, name="ccaas-client", daemon=True
        )
        self.thread.start()

    @classmethod
    def get(cls) -> "_CCaaSLoop":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance


class CCaaSProxy(Contract):
    """Registered in the peer's ChaincodeRuntime under the chaincode
    name; forwards invocations to the external server and serves state
    callbacks against the PEER-side simulator stub (so the rwset is
    built exactly as with in-process contracts)."""

    def __init__(self, chaincode: str, host: str, port: int):
        self.chaincode = chaincode
        self.host, self.port = host, port

    def invoke(self, stub) -> Response:
        runner = _CCaaSLoop.get()

        async def session():
            cli = RpcClient(self.host, self.port)
            await cli.connect()
            try:
                stream = await cli.open_stream("CCInvoke")
                await stream.send(json.dumps({
                    "chaincode": self.chaincode,
                    "args": [a.hex() for a in stub.args],
                    "transient": {k: v.hex() for k, v in stub.transient.items()},
                    "creator": stub.creator.hex(),
                }).encode())
                async for raw in stream:
                    msg = json.loads(raw)
                    if "done" in msg:
                        d = msg["done"]
                        return Response(
                            status=int(d.get("status", 500)),
                            payload=_unhx(d.get("payload")) or b"",
                            message=d.get("message", ""),
                        )
                    result = self._serve(stub, msg)
                    await stream.send(json.dumps({"result": result}).encode())
                return Response(status=500, message="chaincode stream ended early")
            finally:
                await cli.close()

        fut = asyncio.run_coroutine_threadsafe(session(), runner.loop)
        return fut.result(60)

    @staticmethod
    def _serve(stub, msg: dict):
        op = msg["op"]
        if op == "get_state":
            return _hx(stub.get_state(msg["key"]))
        if op == "put_state":
            stub.put_state(msg["key"], _unhx(msg["value"]) or b"")
            return True
        if op == "del_state":
            stub.del_state(msg["key"])
            return True
        if op == "get_range":
            return [
                [k, _hx(v.value if hasattr(v, "value") else v)]
                for k, v in stub.get_state_range(
                    msg["start"], msg["end"], msg.get("limit", 0)
                )
            ]
        if op == "get_private":
            return _hx(stub.get_private(msg["coll"], msg["key"]))
        if op == "put_private":
            stub.put_private(msg["coll"], msg["key"], _unhx(msg["value"]) or b"")
            return True
        if op == "set_event":
            stub.set_event(msg["name"], _unhx(msg["payload"]) or b"")
            return True
        raise ValueError(f"unknown chaincode op {op}")

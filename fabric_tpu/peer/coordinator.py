"""Private-data coordinator: the pvt phase of StoreBlock.

Reference: gossip/privdata/coordinator.go:151-237 — after validation,
for every VALID tx that wrote private collections, source the
cleartext (local transient store → pull from peers), VERIFY it against
the committed hashed write-set (sha256(key)/sha256(value) must match
the rwset the endorsers signed), commit cleartext to the pvt state
namespaces + the pvtdata store, and record what's still missing for
the background reconciler (gossip/privdata/reconcile.go)."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field


@dataclass
class PvtResult:
    updates: list = field(default_factory=list)   # (ns$coll, key, value|None, ver)
    store_data: dict = field(default_factory=dict)  # txnum -> {(ns,coll): {k: v}}
    missing: list = field(default_factory=list)   # (txnum, txid, ns, coll)


def _match_cleartext(hashed_writes: dict, cleartext: dict) -> dict | None:
    """hashed_writes: {key_hash: (value_hash, is_delete)};
    cleartext: {key: value}.  → {key: value|None} covering EVERY hashed
    write, or None if any is missing/mismatched (tamper or gap)."""
    by_hash = {}
    for key, value in cleartext.items():
        kh = hashlib.sha256(
            key.encode() if isinstance(key, str) else key
        ).digest()
        by_hash[kh] = (key, value)
    out = {}
    for kh, (vh, is_del) in hashed_writes.items():
        got = by_hash.get(kh)
        if got is None:
            return None
        key, value = got
        if is_del or value is None:
            out[key] = None
            continue
        if hashlib.sha256(value).digest() != vh:
            return None
        out[key] = value
    return out


class PvtDataCoordinator:
    def __init__(self, transient, puller=None):
        """puller: ASYNC callable (txid, block_num, txnum, ns, coll) →
        {key: value} | None — the gossip pull path for data this peer
        never saw at endorsement time."""
        self.transient = transient
        self.puller = puller

    async def gather(self, block_num: int, parsed_txs, tx_filter: bytes) -> PvtResult:
        res = PvtResult()
        for ptx in parsed_txs:
            if ptx.rwset is None or tx_filter[ptx.idx] != 0:
                continue
            clear = None  # lazily loaded per tx
            for ns_name, n in ptx.rwset.ns.items():
                for coll, h in n.hashed.items():
                    writes = h.get("writes", {})
                    if not writes:
                        continue
                    if clear is None:
                        clear = self.transient.get(ptx.txid) if self.transient else {}
                    kv = _match_cleartext(writes, clear.get((ns_name, coll), {}))
                    if kv is None and self.puller is not None:
                        pulled = await self.puller(
                            ptx.txid, block_num, ptx.idx, ns_name, coll
                        )
                        if pulled is not None:
                            kv = _match_cleartext(writes, pulled)
                    if kv is None:
                        res.missing.append((ptx.idx, ptx.txid, ns_name, coll))
                        continue
                    ver = (block_num, ptx.idx)
                    for key, value in kv.items():
                        res.updates.append(
                            (f"{ns_name}${coll}", key, value, ver)
                        )
                    res.store_data.setdefault(ptx.idx, {})[
                        (ns_name, coll)
                    ] = kv
        return res

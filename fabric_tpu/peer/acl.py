"""ACLs: API-resource → channel-policy mapping, enforced with the
requester's SIGNATURE (not just its identity).

Reference: core/aclmgmt — resources like "peer/Propose" map to policy
refs ("/Channel/Application/Writers"); the check evaluates the policy
against the request's signed data (aclmgmt/resourceprovider.go).  The
endorser wires this in front of simulation (endorser.go:315 auth
phase); deliver/query surfaces use Readers."""

from __future__ import annotations

from fabric_tpu.channelconfig import SignedData

PROPOSE = "peer/Propose"
DELIVER = "event/Block"
QUERY = "qscc/GetChainInfo"
SNAPSHOT = "snapshot/submit"

DEFAULT_POLICY_REFS = {
    PROPOSE: "/Channel/Application/Writers",
    DELIVER: "/Channel/Application/Readers",
    QUERY: "/Channel/Application/Readers",
    SNAPSHOT: "/Channel/Application/Admins",
}


class ACLProvider:
    """Evaluates resource policies against a channel's live bundle."""

    def __init__(self, bundle_source, overrides: dict | None = None):
        """bundle_source: zero-arg callable → channelconfig.Bundle —
        the LIVE bundle (config updates rotate it)."""
        self._bundle = bundle_source
        self.refs = {**DEFAULT_POLICY_REFS, **(overrides or {})}

    def check(self, resource: str, identity_bytes: bytes, message: bytes,
              signature: bytes) -> bool:
        """True iff the signer satisfies the resource's policy — the
        signature is over ``message`` (e.g. the proposal bytes), so a
        stolen identity without the key cannot pass."""
        ref = self.refs.get(resource)
        bundle = self._bundle()
        if bundle is None:
            return False  # no policy source → fail CLOSED (aclmgmt)
        if ref is None:
            return True  # unmapped resources follow the open default
        sd = SignedData(identity=identity_bytes, data=message,
                        signature=signature)
        return bundle.policy_manager.evaluate(ref, [sd])

"""Fused device stage for block validation: policy reduction + MVCC in
ONE dispatch consuming the verify batch's device-resident output.

Why fusion is the TPU-shaped design: the naive pipeline syncs the
device twice per block (signature bits → host policy walk → MVCC
dispatch → results).  Each sync pays a full device round trip — painful
on PCIe, brutal over a tunneled device.  Here the boolean signature
vector NEVER leaves the device: stage 2 gathers it per endorsement,
runs the batch-plan policy reduction (fabric_tpu.crypto.policy
compile_plan semantics — counts vs leaf ranks, the vectorized
formulation of cauthdsl's consumption walk), AND-reduces per tx across
namespaces, feeds the result into the MVCC fixpoint as pre_ok, and
returns one packed int8 vector.  One dispatch, one readback, per block.

Exactness: the count-based policy path is exact iff no signature
matches two distinct principal columns (policy.py consumption_safe).
The device computes that predicate per entry and the host REDOES the
rare unsafe blocks on the exact interpreter path (validator fallback) —
fast path stays exact, slow path stays correct.

Reference anchors: plugin dispatch plugindispatcher/dispatcher.go:102,
policy evaluation common/cauthdsl/cauthdsl.go:24-110, MVCC
validation/validator.go:81-118, per-tx fan-out v20/validator.go:193.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from fabric_tpu.crypto import policy as pol
from fabric_tpu.observe import ledger as _ledger
from fabric_tpu.ops import mvcc as mvcc_ops


@dataclass(frozen=True)
class PlanSig:
    """Static (hashable) shape of one policy group inside the fused
    program — the jit cache key component."""

    leaf_principal: tuple
    leaf_rank: tuple
    gates: tuple  # ((n, (child_slots...)), ...)
    n_principals: int
    e_bucket: int
    s_bucket: int


def plan_sig(plan: pol.BatchPlan, e_bucket: int, s_bucket: int) -> PlanSig:
    return PlanSig(
        leaf_principal=tuple(plan.leaf_principal),
        leaf_rank=tuple(plan.leaf_rank),
        gates=tuple((n, tuple(c)) for n, c in plan.gates),
        n_principals=len(plan.principals),
        e_bucket=e_bucket,
        s_bucket=s_bucket,
    )


def _policy_reduce(sig_padded, match, endo_idx, sig: PlanSig):
    """[Eb] (ok, safe) for one policy group.

    sig_padded: [n_sig + 1] bool with a trailing False — endo_idx −1
    (padding) gathers the False lane."""
    n_sig = sig_padded.shape[0] - 1
    idx = jnp.where(endo_idx >= 0, endo_idx, n_sig)
    ev = sig_padded[idx]  # [Eb, S]
    M = match & ev[:, :, None]  # [Eb, S, P]
    counts = M.sum(axis=1)  # [Eb, P]
    cols = jnp.asarray(sorted(set(sig.leaf_principal)), jnp.int32)
    safe = (M[:, :, cols].sum(axis=2) <= 1).all(axis=1)
    leaf_p = jnp.asarray(sig.leaf_principal, jnp.int32)
    ranks = jnp.asarray(sig.leaf_rank, jnp.int32)
    vals = list((ranks[None, :] < counts[:, leaf_p]).T)  # n_leaves × [Eb]
    for n, children in sig.gates:
        acc = jnp.zeros(match.shape[0], jnp.int32)
        for c in children:
            acc = acc + vals[c].astype(jnp.int32)
        vals.append(acc >= n)
    return vals[-1], safe


def _resident_ver_ok(static_p, table, u_pack, read_pv, R: int,
                     u_bucket: int):
    """[T] bool committed-version check computed ON DEVICE from the
    resident version table — the device twin of
    ``VecStaticBlock.ver_ok_from_u`` (bit-equal: same validateKVRead
    reduction, committed rows gathered from ``table`` for resident
    slots and from the host-provided lanes of ``u_pack`` for misses
    and in-flight-overlay overrides).

      table    [cap, 3] i32: present | ver_block | ver_txnum
      u_pack   [Ub, 4] i32: slot (−1 = host lane) | present | vb | vt
      read_pv  [T, R, 3] i32: expected present | vb | vt per read
    """
    slot = u_pack[:, 0]
    use_host = slot < 0
    trow = table[jnp.where(slot >= 0, slot, 0)]          # [Ub, 3]
    urow = jnp.where(use_host[:, None], u_pack[:, 1:4], trow)
    up = jnp.concatenate(
        [urow[:, 0] != 0, jnp.zeros((1,), bool)]
    )  # + sentinel row for padding reads
    uv = jnp.concatenate(
        [urow[:, 1:3], jnp.zeros((1, 2), urow.dtype)]
    )
    rk = static_p[:, :R]                                  # [T, R]
    idx = jnp.where(rk >= 0, rk, u_bucket)
    cp = up[idx]                                          # [T, R]
    cv = uv[idx]                                          # [T, R, 2]
    rp = read_pv[:, :, 0] != 0
    rv = read_pv[:, :, 1:3]
    ver_eq = jnp.all(rv == cv, axis=-1)
    okr = jnp.where(rp & cp, ver_eq, rp == cp)
    return jnp.all(okr | (rk < 0), axis=-1)


def build_stage2(t_bucket: int, n_sig: int, group_sigs: tuple,
                 static_dims: tuple, resident_dims: tuple | None = None):
    """→ jitted stage2(sig_valid, launch_vec, *group_packed,
    static_packed[, table, u_pack, read_pv]) → packed int8.

    Inputs arrive PACKED — one array per H2D transfer (each device_put
    costs ~1 ms of fixed host overhead over the tunnel, so the
    interface is shaped around transfer count, not array count):
      launch_vec    [T, 3] i32: creator_idx | structural | ver_ok_host
      group_packed  [Eb, S·P + S + 1] i32: match | endo_idx | tx_of
      static_packed [T, R + W + 2Q] i32: read/write keys, rq bounds
    Output layout (host unpacks by static offsets):
      [0:T]    valid        [T:2T]  conflict      [2T:3T] phantom
      [3T:4T]  creator_ok   [4T:5T] policy_ok
      [5T:5T+n_sig] sig_valid
      then per group: [Eb] safe bits.

    ``resident_dims`` = (u_bucket, capacity) compiles the
    DEVICE-RESIDENT state variant (fabric_tpu/state): launch_vec's
    ver_ok column is ignored and the per-read committed-version check
    runs on device against the resident version table
    (:func:`_resident_ver_ok`) — the host ``state_fill`` gather only
    covers the miss/overlay lanes shipped inside ``u_pack``.
    """
    R, W, Q = static_dims

    def stage2(sig_valid, launch_vec, *rest):
        g = len(group_sigs)
        gpacked = rest[:g]
        static_p = rest[g]
        creator_idx = launch_vec[:, 0]
        structural_ok = launch_vec[:, 1] != 0
        if resident_dims is not None:
            table, u_pack, read_pv = rest[g + 1:g + 4]
            ver_ok = _resident_ver_ok(
                static_p, table, u_pack, read_pv, R, resident_dims[0]
            )
        else:
            ver_ok = launch_vec[:, 2] != 0
        # two sentinel lanes past the batch: n_sig = missing creator
        # (False), n_sig+1 = HOST-verified creator (True — idemix
        # identities have no batch lane; validator encodes them as -2)
        svF = jnp.concatenate([
            sig_valid, jnp.zeros((1,), bool), jnp.ones((1,), bool),
        ])
        ns = sig_valid.shape[0]
        creator_ok = svF[jnp.where(
            creator_idx >= 0, creator_idx,
            jnp.where(creator_idx == -2, ns + 1, ns),
        )]

        policy_ok = jnp.ones(t_bucket + 1, jnp.int8)
        safes = []
        for gi, sig in enumerate(group_sigs):
            gp = gpacked[gi]
            S, P = sig.s_bucket, sig.n_principals
            match = (gp[:, : S * P] != 0).reshape(-1, S, P)
            endo_idx = gp[:, S * P: S * P + S]
            tx_of = gp[:, -1]
            ok_g, safe_g = _policy_reduce(svF, match, endo_idx, sig)
            safes.append(safe_g)
            t = jnp.where(tx_of >= 0, tx_of, t_bucket)
            contrib = jnp.where(tx_of >= 0, ok_g, True).astype(jnp.int8)
            policy_ok = policy_ok.at[t].min(contrib)
        policy_ok = policy_ok[:t_bucket].astype(bool)

        pre_ok = structural_ok & creator_ok & policy_ok
        valid, conflict, phantom = mvcc_ops.mvcc_validate_hostver(
            static_p[:, :R], ver_ok, static_p[:, R:R + W],
            static_p[:, R + W:R + W + Q], static_p[:, R + W + Q:],
            pre_ok,
        )

        parts = [valid, conflict, phantom, creator_ok, policy_ok, sig_valid]
        parts.extend(safes)
        return jnp.concatenate([p.astype(jnp.int8) for p in parts])

    return jax.jit(stage2)


_PROGRAM_CACHE: dict = {}


class DeviceBlockPipeline:
    """Caches compiled stage-2 programs keyed by static block shape +
    the set of policy plans in play.

    The cache is MODULE-global: the key (buckets + PlanSig tuples) is
    fully structural, so validators across channels/instances share the
    traced program — a fresh validator must not pay a retrace."""

    def __init__(self):
        self._cache = _PROGRAM_CACHE
        from fabric_tpu.ops_metrics import global_registry

        reg = global_registry()
        # stage-2 telemetry: dispatch cost (host side of the fused
        # launch) and the structural-program cache size — a growing
        # gauge on a stable workload means retraces are leaking in
        self._dispatch_hist = reg.histogram(
            "device_stage2_dispatch_seconds",
            "host-side fused stage-2 dispatch time (s)",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, float("inf")),
        )
        self._cache_gauge = reg.gauge(
            "device_stage2_programs", "compiled stage-2 program cache size"
        )
        self._shards_hist = reg.histogram(
            "device_mesh_shards",
            "mesh shards per fused stage-1+stage-2 launch (1 = unsharded)",
            buckets=(1, 2, 4, 8, 16, float("inf")),
        )

    def run(self, handle, launch_vec, groups, static_packed, static_dims,
            pre_ok_pad_len, mesh=None, resident=None):
        """handle: p256v3.VerifyHandle; launch_vec np [T,3] i32;
        groups: list of (plan, packed_dev [Eb, S·P+S+1], Eb, S);
        static_packed: device [T, R+W+2Q] i32; static_dims: (R, W, Q).
        Returns a zero-arg fetch → dict of numpy arrays.

        ``mesh``: parallel.mesh data mesh — the per-tx (launch_vec,
        static_packed) and per-endorsement (group) lanes shard axis 0
        over it; XLA gathers the policy scatter-min and the MVCC
        fixpoint's validity vector with collectives.  The signature
        vector (``handle.device_out``) keeps whatever sharding the
        verify dispatch gave it.  Bit-equal to unsharded: every device
        value is integer/boolean (the f32 fixpoint matvec sums 0/1
        counts < 2^24, exact in any reduction order).

        ``resident``: (table_dev [cap,3] i32, u_pack np [Ub,4] i32,
        read_pv_dev [T,R,3] i32) — the device-resident state operands
        (fabric_tpu/state): the program variant computes ver_ok ON
        DEVICE from the resident version table, launch_vec's ver_ok
        column is inert.  The table keeps whatever sharding the
        residency manager gave it (axis 0 over the same data mesh);
        u_pack is the only launch-time state upload."""
        t_bucket = pre_ok_pad_len
        n_sig = int(handle.device_out.shape[0])
        gsigs = tuple(
            plan_sig(plan, eb, s) for plan, _, eb, s in groups
        )
        resident_dims = None
        if resident is not None:
            table_dev, u_pack, read_pv_dev = resident
            resident_dims = (int(u_pack.shape[0]),
                             int(table_dev.shape[0]))
        key = (t_bucket, n_sig, gsigs, static_dims, resident_dims)
        fn = self._cache.get(key)
        compiled = fn is None
        if compiled:
            fn = self._cache[key] = build_stage2(
                t_bucket, n_sig, gsigs, static_dims,
                resident_dims=resident_dims,
            )
            self._cache_gauge.set(len(self._cache))
        # launch ledger (observe/ledger.py): the program-cache verdict
        # is EXACT here — this class owns the cache.  The launch-time
        # H2D is the packed launch vector (+ the resident slot frame);
        # groups/static uploaded from the prefetch thread already.
        h2d = launch_vec.nbytes
        if resident is not None:
            h2d += resident[1].nbytes
        from fabric_tpu.parallel import mesh as pmesh

        # partition-rule verdict BEFORE the puts: a mesh-configured
        # dispatch whose per-tx planes cannot shard (ragged axis 0)
        # runs single-device — tag the ledger row so /launches shows
        # it instead of mystery device_wait (untagged when no mesh)
        sharded = None
        if mesh is not None:
            data_planes = [launch_vec, static_packed]
            data_planes += [gp for _, gp, _, _ in groups]
            if resident is not None:
                data_planes.append(resident[2])
            sharded = all(pmesh.will_shard(mesh, a) for a in data_planes)
        rec = _ledger.launch("stage2", compiled=compiled,
                             lanes=t_bucket, h2d_bytes=h2d,
                             sharded=sharded)
        # the fused path never calls the verify handle's fetch (the
        # signature vector stays on device as a stage-2 operand), so
        # its ledger record would never close: complete it
        # enqueue-only here — its compile/dispatch/h2d stand, and the
        # fused chain's device time is owned by THIS record's sync
        # (splitting verify execute out of one fused dependency chain
        # is not host-observable, so the ledger does not pretend to)
        vrec = getattr(handle, "rec", None)
        if vrec is not None:
            vrec.complete()
        t0 = time.perf_counter()
        self._shards_hist.observe(pmesh.data_axis_size(mesh))
        # every operand goes up under its family's partition rule
        # (fabric_tpu/parallel/mesh.py) — the declarative table is the
        # single sharding authority (FT019 polices the boundary)
        args = [handle.device_out,
                pmesh.shard(mesh, "launch_frame",
                            jnp.asarray(launch_vec))]
        args += [pmesh.shard(mesh, "policy_table", gp)
                 for _, gp, _, _ in groups]
        args += [pmesh.shard(mesh, "static_pack", static_packed)]
        if resident is not None:
            # table keeps the manager's key-range sharding; u_pack is
            # per-key (not per-tx) so it rides replicated — it is tiny
            args += [table_dev,
                     pmesh.shard(mesh, "unique_read_pack",
                                 jnp.asarray(u_pack)),
                     pmesh.shard(mesh, "read_versions", read_pv_dev)]
        from fabric_tpu.observe import device_annotation

        if rec is not None:
            # transient HBM pin: this block's launch frames (verify
            # output + packed operands) pinned on device until the
            # fetch — ADDITIVE, so depth-N concurrent blocks sum and
            # the watermark records the true concurrent peak; released
            # when the record completes
            rec.pin_hbm("launch_frames", sum(
                int(getattr(a, "nbytes", 0)) for a in args
            ))
        # lines the fused stage-2 dispatch up with the XLA timeline
        # when a jax profiler capture is running (real-TPU rounds)
        with device_annotation("fabtpu.stage2_dispatch"):
            packed = fn(*args)
        if hasattr(packed, "copy_to_host_async"):
            packed.copy_to_host_async()
        if rec is not None:
            rec.dispatched()
            rec.pin_hbm("outputs", int(getattr(packed, "nbytes", 0)))
        self._dispatch_hist.observe(time.perf_counter() - t0)

        def fetch():
            if rec is not None:
                rec.sync_begin()
            flat = np.asarray(packed)
            if rec is not None:
                rec.sync_end(d2h_bytes=flat.nbytes)
            flat = flat.astype(bool)
            T = t_bucket
            out = {
                "valid": flat[0:T],
                "conflict": flat[T:2 * T],
                "phantom": flat[2 * T:3 * T],
                "creator_ok": flat[3 * T:4 * T],
                "policy_ok": flat[4 * T:5 * T],
                "sig_valid": flat[5 * T:5 * T + n_sig],
            }
            off = 5 * T + n_sig
            safes = []
            for sig in gsigs:
                safes.append(flat[off:off + sig.e_bucket])
                off += sig.e_bucket
            out["safe"] = safes
            return out

        return fetch

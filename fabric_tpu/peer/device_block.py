"""Fused device stage for block validation: policy reduction + MVCC in
ONE dispatch consuming the verify batch's device-resident output.

Why fusion is the TPU-shaped design: the naive pipeline syncs the
device twice per block (signature bits → host policy walk → MVCC
dispatch → results).  Each sync pays a full device round trip — painful
on PCIe, brutal over a tunneled device.  Here the boolean signature
vector NEVER leaves the device: stage 2 gathers it per endorsement,
runs the batch-plan policy reduction (fabric_tpu.crypto.policy
compile_plan semantics — counts vs leaf ranks, the vectorized
formulation of cauthdsl's consumption walk), AND-reduces per tx across
namespaces, feeds the result into the MVCC fixpoint as pre_ok, and
returns one packed int8 vector.  One dispatch, one readback, per block.

Exactness: the count-based policy path is exact iff no signature
matches two distinct principal columns (policy.py consumption_safe).
The device computes that predicate per entry and the host REDOES the
rare unsafe blocks on the exact interpreter path (validator fallback) —
fast path stays exact, slow path stays correct.

Reference anchors: plugin dispatch plugindispatcher/dispatcher.go:102,
policy evaluation common/cauthdsl/cauthdsl.go:24-110, MVCC
validation/validator.go:81-118, per-tx fan-out v20/validator.go:193.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from fabric_tpu.crypto import policy as pol
from fabric_tpu.ops import mvcc as mvcc_ops


@dataclass(frozen=True)
class PlanSig:
    """Static (hashable) shape of one policy group inside the fused
    program — the jit cache key component."""

    leaf_principal: tuple
    leaf_rank: tuple
    gates: tuple  # ((n, (child_slots...)), ...)
    n_principals: int
    e_bucket: int
    s_bucket: int


def plan_sig(plan: pol.BatchPlan, e_bucket: int, s_bucket: int) -> PlanSig:
    return PlanSig(
        leaf_principal=tuple(plan.leaf_principal),
        leaf_rank=tuple(plan.leaf_rank),
        gates=tuple((n, tuple(c)) for n, c in plan.gates),
        n_principals=len(plan.principals),
        e_bucket=e_bucket,
        s_bucket=s_bucket,
    )


def _policy_reduce(sig_padded, match, endo_idx, sig: PlanSig):
    """[Eb] (ok, safe) for one policy group.

    sig_padded: [n_sig + 1] bool with a trailing False — endo_idx −1
    (padding) gathers the False lane."""
    n_sig = sig_padded.shape[0] - 1
    idx = jnp.where(endo_idx >= 0, endo_idx, n_sig)
    ev = sig_padded[idx]  # [Eb, S]
    M = match & ev[:, :, None]  # [Eb, S, P]
    counts = M.sum(axis=1)  # [Eb, P]
    cols = jnp.asarray(sorted(set(sig.leaf_principal)), jnp.int32)
    safe = (M[:, :, cols].sum(axis=2) <= 1).all(axis=1)
    leaf_p = jnp.asarray(sig.leaf_principal, jnp.int32)
    ranks = jnp.asarray(sig.leaf_rank, jnp.int32)
    vals = list((ranks[None, :] < counts[:, leaf_p]).T)  # n_leaves × [Eb]
    for n, children in sig.gates:
        acc = jnp.zeros(match.shape[0], jnp.int32)
        for c in children:
            acc = acc + vals[c].astype(jnp.int32)
        vals.append(acc >= n)
    return vals[-1], safe


def build_stage2(t_bucket: int, n_sig: int, group_sigs: tuple):
    """→ jitted stage2(sig_valid, creator_idx, structural_ok,
    *per-group (match, endo_idx, tx_of), *mvcc_arrays, ) → packed int8.

    Packed layout (host unpacks by static offsets):
      [0:T]    valid        [T:2T]  conflict      [2T:3T] phantom
      [3T:4T]  creator_ok   [4T:5T] policy_ok
      [5T:5T+n_sig] sig_valid
      then per group: [Eb] safe bits.
    """

    def stage2(sig_valid, creator_idx, structural_ok, *rest):
        g = len(group_sigs)
        groups = rest[: 3 * g]
        mvcc_arrays = rest[3 * g :]
        # two sentinel lanes past the batch: n_sig = missing creator
        # (False), n_sig+1 = HOST-verified creator (True — idemix
        # identities have no batch lane; validator encodes them as -2)
        svF = jnp.concatenate([
            sig_valid, jnp.zeros((1,), bool), jnp.ones((1,), bool),
        ])
        ns = sig_valid.shape[0]
        creator_ok = svF[jnp.where(
            creator_idx >= 0, creator_idx,
            jnp.where(creator_idx == -2, ns + 1, ns),
        )]

        policy_ok = jnp.ones(t_bucket + 1, jnp.int8)
        safes = []
        for gi, sig in enumerate(group_sigs):
            match, endo_idx, tx_of = groups[3 * gi : 3 * gi + 3]
            ok_g, safe_g = _policy_reduce(svF, match, endo_idx, sig)
            safes.append(safe_g)
            t = jnp.where(tx_of >= 0, tx_of, t_bucket)
            contrib = jnp.where(tx_of >= 0, ok_g, True).astype(jnp.int8)
            policy_ok = policy_ok.at[t].min(contrib)
        policy_ok = policy_ok[:t_bucket].astype(bool)

        pre_ok = structural_ok & creator_ok & policy_ok
        valid, conflict, phantom = mvcc_ops.mvcc_validate_hostver(
            *mvcc_arrays, pre_ok
        )

        parts = [valid, conflict, phantom, creator_ok, policy_ok, sig_valid]
        parts.extend(safes)
        return jnp.concatenate([p.astype(jnp.int8) for p in parts])

    return jax.jit(stage2)


_PROGRAM_CACHE: dict = {}


class DeviceBlockPipeline:
    """Caches compiled stage-2 programs keyed by static block shape +
    the set of policy plans in play.

    The cache is MODULE-global: the key (buckets + PlanSig tuples) is
    fully structural, so validators across channels/instances share the
    traced program — a fresh validator must not pay a retrace."""

    def __init__(self):
        self._cache = _PROGRAM_CACHE

    def run(self, handle, creator_idx, structural_ok, groups, mvcc_arrays,
            pre_ok_pad_len):
        """handle: p256v3.VerifyHandle; groups: list of
        (plan, match np[Eb,S,P], endo_idx np[Eb,S], tx_of np[Eb]).
        Returns a zero-arg fetch → dict of numpy arrays."""
        t_bucket = pre_ok_pad_len
        n_sig = int(handle.device_out.shape[0])
        gsigs = tuple(
            plan_sig(plan, match.shape[0], match.shape[1])
            for plan, match, _, _ in groups
        )
        mshapes = tuple(tuple(a.shape) for a in mvcc_arrays)
        key = (t_bucket, n_sig, gsigs, mshapes)
        fn = self._cache.get(key)
        if fn is None:
            fn = self._cache[key] = build_stage2(t_bucket, n_sig, gsigs)
        args = [handle.device_out, jnp.asarray(creator_idx),
                jnp.asarray(structural_ok)]
        for _, match, endo_idx, tx_of in groups:
            args += [jnp.asarray(match), jnp.asarray(endo_idx),
                     jnp.asarray(tx_of)]
        args += [jnp.asarray(a) for a in mvcc_arrays]
        packed = fn(*args)
        if hasattr(packed, "copy_to_host_async"):
            packed.copy_to_host_async()

        def fetch():
            flat = np.asarray(packed).astype(bool)
            T = t_bucket
            out = {
                "valid": flat[0:T],
                "conflict": flat[T:2 * T],
                "phantom": flat[2 * T:3 * T],
                "creator_ok": flat[3 * T:4 * T],
                "policy_ok": flat[4 * T:5 * T],
                "sig_valid": flat[5 * T:5 * T + n_sig],
            }
            off = 5 * T + n_sig
            safes = []
            for sig in gsigs:
                safes.append(flat[off:off + sig.e_bucket])
                off += sig.e_bucket
            out["safe"] = safes
            return out

        return fetch

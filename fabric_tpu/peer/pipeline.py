"""The production commit pipeline: depth-N block overlap as a
reusable subsystem shared by the peer node's deliver loop and bench.py.

Shape at depth 3 (the TPU analog of the reference peer's deliver
prefetch + committer overlap, gossip/state/state.go:540 + the
validator pool, v20/validator.go:193):

    prefetch thread   preprocess(block n+1)      host parse + async
                                                 device verify launch
    caller thread     validate_finish(block n-1) device sync → filter
                      validate_launch(block n)   overlay = merged
                                                 batches of n-1, n-2
    committer thread  commit(block n-1)          ledger commit
                      commit(block n-2)          …still fsyncing

While block n sits on device and up to ``depth - 1`` predecessors'
ledger commits drain in order on the committer thread, the prefetch
thread parses block n+1.  The in-flight predecessors' UpdateBatches
ride along as a MERGED *overlay* on block n's launch (newest-wins key
resolution — ``ledger.statedb.UpdateBatch.merged`` — feeding the
committed-version fill, range re-execution and SBE probes), and the
duplicate-txid window widens to every in-flight predecessor's txid
set, so launch(n) never waits for any predecessor's fsync.  Depth 2
degrades to the classic single-overlay overlap (pointer-identical
batch, same wait points); the overlay equivalence at every depth is
pinned by tests/test_pipeline.py and tests/test_commit_pipeline.py.

At depth ≥ 3, commits handed to the committer thread are marked
``defer_sync``: the ledger's group-commit machinery batches their
fsyncs across the pipeline window, and the window closes (forced
sync) at every barrier, stream-idle flush, and tail — the
crash-replay story is the blockstore's (ledger/blockstore.py
group_commit): a kill mid-window reopens at the last synced boundary
and replays forward.  Depth 2 never defers — the default config keeps
the classic per-block acknowledged-durability fsync exactly.

Lifecycle/config barrier: blocks that rotate validation inputs —
CONFIG txs (MSP/policy object rotation) and blocks writing the
``_lifecycle`` namespace (state-backed chaincode definitions feed the
preprocess-time policy plans) — must commit FULLY before the next
block launches, with the overlay dropped.  ``CommitPipeline`` owns
that rule so no caller can get it wrong (validate_launch also refuses
a lifecycle-writing overlay as a backstop).

``depth=1`` degrades to the strict serial launch→finish→commit order —
the correctness oracle, kept behind the ``pipeline_depth`` node config
knob.

Overlap telemetry rides the process metrics registry
(fabric_tpu.ops_metrics) so the bench breakdown and production
telemetry agree:

* ``commit_pipeline_stage_seconds{stage=...}`` — prefetch_wait /
  finish / commit_wait / launch per block,
* ``commit_pipeline_overlap_ratio`` — 1 − blocked/total per block
  (1.0 = the pipeline never stalled on prefetch or the committer),
* ``commit_pipeline_inflight`` — blocks in flight (launched or
  committing),
* ``commit_pipeline_blocks_total{mode=...}`` — pipelined / barrier /
  serial block counts.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from concurrent.futures import TimeoutError as _CfTimeout

from fabric_tpu import faults as _faults
from fabric_tpu.observe import txflow as _txflow

_log = logging.getLogger("fabric_tpu.pipeline")

#: seconds between "still waiting" warnings while blocked on a worker
#: thread — bounded-wait discipline (FT009): a wedged fsync or a hung
#: prefetch must be VISIBLE in logs, not a silently frozen feeder
WAIT_WARN_S = 60.0


def _wait_result(fut, what: str, channel: str = ""):
    """``fut.result()`` as a bounded poll: same blocking semantics (a
    legitimately slow commit still completes), but a warning fires
    every ``WAIT_WARN_S`` so a wedged worker thread is diagnosable."""
    waited = 0.0
    while True:
        try:
            return fut.result(timeout=WAIT_WARN_S)
        except _CfTimeout:
            if fut.done():
                # the future completed in the race window while our
                # poll timeout propagated (or, py3.11+, the WORK itself
                # raised builtin TimeoutError) — a done future answers
                # non-blocking with the real value or the real error,
                # never with our poll timeout
                return fut.result()
            waited += WAIT_WARN_S
            _log.warning(
                "%s: still waiting on the %s worker after %.0fs — "
                "thread wedged? (/debug/stacks names it)",
                channel or "pipeline", what, waited,
            )


@dataclass
class CommittedBlock:
    """One block through the pipeline: the validated triple plus the
    PendingBlock handle (``pend.txs`` carries the parsed records for
    post-commit consumers; ``pend.hd_bytes`` the pre-serialized
    header+data for the ledger)."""

    block: object
    pend: object
    tx_filter: bytes
    batch: object
    history: list
    barrier: bool = False
    # True when this commit runs on the committer thread of a DEPTH ≥ 3
    # pipeline with more of the window behind it: the commit_fn may
    # SKIP its forced per-block fsync and let the blockstore's
    # group-commit batch the syncs across the window (force-closed at
    # every barrier, idle flush, and tail — those commits carry False,
    # and depth ≤ 2 never defers: classic durability unchanged)
    defer_sync: bool = False
    # filled by the pipeline for telemetry (seconds)
    stage_s: dict = field(default_factory=dict)
    # this block's tracer root span (fabric_tpu.observe) — commit_fn
    # implementations hang their ledger-commit/fsync spans off it
    # explicitly (the commit may hop to an event-loop thread, where
    # the committer thread's span attachment cannot follow)
    root_span: object = None

    @property
    def txids(self) -> list:
        """[(txid, idx)] for the ledger's txid index."""
        return [(p.txid, p.idx) for p in self.pend.txs if p.txid]

    @property
    def n_valid(self) -> int:
        return sum(1 for c in self.tx_filter if c == 0)


class _SliceFuture:
    """One block's slice of a coalesced prefetch future — quacks like
    the per-block Future ``_launch_next`` expects."""

    __slots__ = ("fut", "i")

    def __init__(self, fut, i: int):
        self.fut = fut
        self.i = i

    def result(self, timeout=None):
        return self.fut.result(timeout)[self.i]

    def done(self) -> bool:
        return self.fut.done()


def _is_barrier(pend, batch) -> bool:
    """True for blocks that rotate validation inputs: commit fully,
    drop the overlay, before the successor may launch."""
    return batch.touches_namespace("_lifecycle") or any(
        p.is_config for p in pend.txs
    )


@dataclass
class _InflightCommit:
    """One predecessor whose ledger commit is in flight on the
    committer thread — its batch joins the merged launch overlay and
    its txids the widened dup window until the commit is drained."""

    fut: object       # committer-thread Future
    batch: object     # the block's UpdateBatch (overlay chain member)
    txids: object     # the block's txid set (dup-window member)
    number: int


class CommitPipeline:
    """Streaming depth-N commit pipeline over a BlockValidator.

    ``depth`` is the number of blocks in flight: 1 = strict serial
    (the correctness oracle), 2 = the classic overlap (one launched +
    one committing, single-batch overlay), N ≥ 3 = a deep window where
    up to N−1 predecessors' commits drain on the committer thread
    while the newest block launches under a MERGED overlay of their
    batches (``UpdateBatch.merged``, newest-wins) and a dup-txid
    window spanning all of them — block n on device while n−1 commits
    and n−2 fsyncs.  The committer thread serializes commits in block
    order at every depth; a barrier (or flush) drains the whole
    window before proceeding.

    ``submit(block)`` feeds the next block in height order and returns
    the COMPLETED predecessor (its commit handed to the committer
    thread — or fully flushed for barriers/serial mode), or None while
    the pipe fills.  ``flush()`` drains the in-flight tail.  Use as a
    context manager, or call ``close()``; both flush unless told not
    to.

    ``commit_fn(res: CommittedBlock)`` runs on the committer thread
    (inline for barriers and in serial mode) and must perform the
    ledger commit; commits are serialized in block order and a commit
    failure surfaces at the next ``submit``/``flush``.  At depth ≥ 3,
    pipelined commits carry ``res.defer_sync=True`` — a commit_fn may
    skip its forced per-block fsync for those and let the blockstore's
    group-commit batch the syncs over the window (barrier/tail/idle
    commits carry False, closing the window; depth ≤ 2 never defers).

    ``prefetch_fn(block)`` (default ``validator.preprocess``) runs on
    the prefetch thread.  ``pre_launch_fn(block)`` runs on the CALLER
    thread immediately before the block's launch — the node hangs
    orderer block-signature verification here, NOT on the prefetch
    thread, because the barrier guarantees a predecessor CONFIG block
    has fully committed (bundle rotated) by launch time, while
    prefetch overlaps that commit and would verify against the
    pre-rotation orderer set.

    ``coalesce_blocks`` ≥ 2 turns on multi-block launch coalescing:
    ``submit_many`` stages up to that many waiting blocks' signature
    batches as ONE concatenated verify dispatch
    (validator.preprocess_many → ops.p256v3.verify_launch_many),
    amortizing the ladder's dispatch latency over the backlog; each
    block then flows through the normal depth-2 launch/finish/commit
    machinery on its own slice of the device output, so overlays,
    barriers and dup-txid windows behave exactly as with per-block
    prefetch.  Needs a real accelerator to win (like ``verify_chunk``);
    off (0) by default.
    """

    def __init__(self, validator, commit_fn, depth: int = 2,
                 prefetch_fn=None, pre_launch_fn=None, registry=None,
                 channel: str = "", coalesce_blocks: int = 0,
                 tracer=None, replay: bool = False):
        self.validator = validator
        self.commit_fn = commit_fn
        # replay pipelines (peer/replay.py) tag their tx-flow
        # inclusion stamps so catch-up blocks record inclusion→apply
        # only and never inherit a colliding live flow's endorse legs
        self.replay = bool(replay)
        if tracer is None:
            from fabric_tpu.observe import global_tracer

            tracer = global_tracer()
        # span tracer (fabric_tpu.observe): one root span per block
        # (submit → commit complete) with prefetch/launch/finish/commit
        # children across the three threads — the flight recorder and
        # /trace read what this records
        self.tracer = tracer
        # 1 = serial oracle; N ≥ 2 = up to N−1 in-flight predecessor
        # commits, their batches merged into the launch overlay
        self.depth = max(1, int(depth))
        self.prefetch_fn = prefetch_fn or validator.preprocess
        self.pre_launch_fn = pre_launch_fn
        self.coalesce_blocks = int(coalesce_blocks)
        # coalescing rides the validator's preprocess_many; a CUSTOM
        # prefetch_fn has no coalesced form, so submit_many degrades
        # to per-block submits there
        self._prefetch_many_fn = (
            getattr(validator, "preprocess_many", None)
            if prefetch_fn is None else None
        )
        self.channel = channel
        if registry is None:
            from fabric_tpu.ops_metrics import global_registry

            registry = global_registry()
        self._stage_hist = registry.histogram(
            "commit_pipeline_stage_seconds",
            "per-block commit pipeline stage time (s)",
        )
        self._overlap_hist = registry.histogram(
            "commit_pipeline_overlap_ratio",
            "1 - blocked/total per pipelined block",
            buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 1.0,
                     float("inf")),
        )
        self._inflight_gauge = registry.gauge(
            "commit_pipeline_inflight", "blocks launched or committing"
        )
        self._blocks_ctr = registry.counter(
            "commit_pipeline_blocks_total", "blocks through the pipeline"
        )
        self._stage_fail_ctr = registry.counter(
            "commit_pipeline_stage_failures_total",
            "pipeline stage exceptions by stage",
        )
        # (block_number, stage) of the most recent stage failure — the
        # deliver driver reads this to log WHICH block was quarantined
        # when it drains the pipe and resumes from committed height
        self.last_failure: tuple | None = None
        self._prefetch = ThreadPoolExecutor(
            1, thread_name_prefix="fabtpu-prefetch"
        )
        self._committer = ThreadPoolExecutor(
            1, thread_name_prefix="fabtpu-committer"
        )
        self._pre: tuple | None = None   # (block, prefetch Future, root)
        self._launched = None                # PendingBlock in flight
        self._launched_root = None           # its tracer root span
        # in-flight predecessor commits, oldest first: at most depth−1
        # deep; their batches form the merged launch overlay and their
        # txids the widened dup window
        self._commits: deque[_InflightCommit] = deque()
        # set when a barrier flushed AFTER the next block was already
        # staged on the prefetch thread — that prefetch ran against
        # pre-barrier state and must be redone (see _launch_next)
        self._stale_prefetch = False
        # the in-flight block's own launch duration, attached to its
        # CommittedBlock at finish so per-block metrics keep covering
        # launch+finish under pipelining (prefetch parse overlaps the
        # predecessor and is deliberately excluded)
        self._launch_s = 0.0
        # runtime re-knobbing (the traffic autopilot's actuators):
        # set_depth/set_coalesce_blocks latch a pending value that is
        # applied at the NEXT submit boundary — never mid-window, so a
        # block's launch/finish/commit always runs under one knob
        # vector.  GIL-atomic attribute writes; no lock needed.
        self._pending_depth: int | None = None
        self._pending_coalesce: int | None = None
        self._closed = False

    # -- runtime re-knobbing (autopilot actuators) -------------------------

    def set_depth(self, depth: int) -> None:
        """Request a new pipeline depth, applied at the next submit
        boundary (never mid-window).  A serial pipe (depth 1) stays
        serial — the pipelined/serial boundary owns thread lifecycles
        and cannot be crossed at runtime — and a pipelined pipe never
        drops below 2 for the same reason; deeper→shallower simply
        drains the excess window at the next finish."""
        if self.depth <= 1:
            return
        self._pending_depth = max(2, int(depth))

    def set_coalesce_blocks(self, n: int) -> None:
        """Request a new multi-block coalescing group size, applied at
        the next submit boundary.  Coalescing needs the validator's
        ``preprocess_many``; without it the knob stays inert exactly
        as at construction."""
        n = int(n)
        self._pending_coalesce = 0 if n < 2 else n

    def _apply_pending_knobs(self) -> None:
        """Block boundary: adopt any latched knob values.  Called at
        the top of submit/submit_many, where no block is mid-stage on
        the caller thread."""
        d = self._pending_depth
        if d is not None:
            self._pending_depth = None
            if d != self.depth:
                self.depth = d
        c = self._pending_coalesce
        if c is not None:
            self._pending_coalesce = None
            if c != self.coalesce_blocks:
                self.coalesce_blocks = c

    # -- failure containment ----------------------------------------------

    def _note_stage_failure(self, stage: str, block_num) -> None:
        """Record a stage exception (counter + quarantine pointer) on
        its way out; the exception itself keeps propagating."""
        self.last_failure = (block_num, stage)
        self._stage_fail_ctr.add(1, channel=self.channel, stage=stage)
        _log.warning(
            "%s: pipeline %s stage failed for block %s — pipe will "
            "drain and fail closed; resume from committed height",
            self.channel or "pipeline", stage, block_num,
        )

    def _fail_closed(self) -> None:
        """A stage exception left the pipe mid-flight: drop the
        in-flight state, drain both worker threads, and latch closed so
        the NEXT submit raises 'pipeline is closed' cleanly instead of
        tripping internal asserts.  The caller (deliver driver, bench
        chaos harness) rebuilds a fresh pipeline and resumes from the
        last committed height — the replay check skips what already
        landed.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        # incident edge: a quarantined block is the attribution case
        # the flight-data recorder exists for — bundle the trailing
        # series + trace trees before the in-flight state is dropped
        from fabric_tpu.observe import blackbox

        failure = self.last_failure
        blackbox.notify(
            "pipeline_fail_closed", channel=self.channel,
            block=failure[0] if failure else None,
            stage=failure[1] if failure else None,
        )
        self._pre = None
        self._launched = None
        self._launched_root = None
        # still-pending committer tasks finish inside shutdown's wait;
        # their errors (if any) were either surfaced already or are
        # superseded by the failure that got us here
        self._commits.clear()
        self._stale_prefetch = False
        self._prefetch.shutdown(wait=True)
        self._committer.shutdown(wait=True)
        self._inflight_gauge.set(0, channel=self.channel)

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # on an exception unwind, don't let a flush failure mask it
        self.close(flush=exc_type is None)
        return False

    def close(self, flush: bool = True):
        """Shut the worker threads down; with ``flush`` (default) the
        in-flight tail commits first."""
        if self._closed:
            return None
        res = None
        try:
            if flush:
                res = self.flush()
        finally:
            self._closed = True
            self._prefetch.shutdown(wait=True)
            self._committer.shutdown(wait=True)
            self._inflight_gauge.set(0, channel=self.channel)
        return res

    @property
    def inflight(self) -> int:
        """Blocks accepted but not yet fully committed (prefetched,
        launched, or draining on the committer thread) — feeds the
        ``commit_pipeline_inflight`` gauge and the deliver driver's
        idle-flush decision.  (Replay protection in the deliver loop
        tracks the next expected block number directly; it does not
        consume this.)"""
        return ((self._pre is not None) + (self._launched is not None)
                + len(self._commits))

    # -- the in-flight commit window ---------------------------------------

    def _drain_commits(self, keep: int) -> None:
        """Wait out in-flight predecessor commits (oldest first) until
        at most ``keep`` remain.  Records are POPPED before waiting so
        a commit error surfaces exactly once; 0 = full drain (barrier,
        tail, flush)."""
        while len(self._commits) > keep:
            rec = self._commits.popleft()
            _wait_result(rec.fut, "committer", self.channel)

    def _launch_overlay(self):
        """(overlay, extra_txids) for the next launch, derived from the
        in-flight commit window: a singleton window hands the batch and
        txid set through UNMERGED (the depth-2 fast path — pointer
        identity preserved); deeper windows merge newest-wins and union
        the dup-txid sets."""
        if not self._commits:
            return None, None
        if len(self._commits) == 1:
            rec = self._commits[0]
            return rec.batch, rec.txids
        from fabric_tpu.ledger.statedb import UpdateBatch

        recs = list(self._commits)
        return (
            UpdateBatch.merged([r.batch for r in recs]),
            set().union(*(set(r.txids) for r in recs)),
        )

    # -- the pipeline ------------------------------------------------------

    def submit(self, block):
        """Feed the next block (height order).  Depth-2: returns the
        predecessor's CommittedBlock (commit in flight on the
        committer thread unless it was a barrier) or None while the
        pipe fills.  Serial (depth=1): validates AND commits ``block``
        inline, returning its CommittedBlock.

        A stage exception FAILS THE PIPE CLOSED (see ``_fail_closed``):
        it surfaces here exactly once, the worker threads drain, and
        the next submit raises 'pipeline is closed' — callers rebuild a
        fresh pipeline and resume from the last committed height."""
        if self._closed:
            raise RuntimeError("pipeline is closed")
        self._apply_pending_knobs()
        try:
            if self.depth == 1:
                return self._submit_serial(block)
            t_sub = time.perf_counter()
            # stage the new block on the prefetch thread FIRST: its
            # host parse + device verify launch overlap the
            # predecessor's device sync below
            assert self._pre is None, (
                "submit() before the previous returned"
            )
            root = self.tracer.begin_block(block.header.number,
                                           channel=self.channel)
            self._pre = (
                block,
                self._prefetch.submit(self._prefetch_traced, block, root),
                root,
            )
            self._inflight_gauge.set(self.inflight, channel=self.channel)

            out = None
            if self._launched is not None:
                out = self._finish_and_commit(self._launched)
            self._launch_next(
                out.stage_s if out is not None else {}, t_sub
            )
            return out
        except BaseException:
            self._fail_closed()
            raise

    def _prefetch_traced(self, block, root):
        """Prefetch-thread task: the explicit span handle crosses the
        executor boundary here (contextvars would not), and the span's
        attachment makes the validator's parse/device_pre stage timers
        and any host-pool worker tasks nest under it."""
        with self.tracer.span("prefetch", parent=root):
            _faults.fire("pipeline.prefetch")
            return self.prefetch_fn(block)

    def _prefetch_many_traced(self, group, root, n):
        with self.tracer.span("prefetch", parent=root, coalesced=n):
            _faults.fire("pipeline.prefetch")
            return self._prefetch_many_fn(group)

    def _resident_commit(self, res) -> None:
        """Device-resident state (fabric_tpu/state): apply the
        committed block's write-set delta to the validator's resident
        version table AT the commit boundary — strictly before the
        commit future resolves, so a successor launch whose overlay no
        longer covers this block has happens-before ordering with the
        scatter (the coherence contract in state/residency.py), while
        a launch whose overlay still covers it forces the same keys
        onto overlay-valued host lanes either way.  Validators without
        the hook (toy validators, custom prefetchers) skip free."""
        fn = getattr(self.validator, "resident_commit", None)
        if fn is not None:
            fn(res.batch)

    def _run_commit(self, res) -> None:
        """The ONE commit body shared by all three commit sites
        (pipelined committer thread, serial mode, barrier/tail
        inline): stamp tx-flow inclusion + verdicts, then the ledger
        commit and the resident-state scatter.  The inclusion stamp
        lands BEFORE commit_fn so the ledger's durable/apply fences
        find the block's flows already open."""
        if _txflow.enabled():
            num = res.block.header.number
            txs = [(p.txid, int(res.tx_filter[p.idx]))
                   for p in res.pend.txs if p.txid]
            _txflow.block_included(num, txs, channel=self.channel,
                                   replay=self.replay)
        self.commit_fn(res)
        self._resident_commit(res)

    def _commit_traced(self, res, root):
        """Committer-thread task: commit under its span, then finalize
        the block's root — ring append + slow-block watchdog run here,
        off the caller thread's critical path."""
        try:
            with self.tracer.span("commit", parent=root):
                _faults.fire("pipeline.commit")
                self._run_commit(res)
        except BaseException:
            self._note_stage_failure("commit", res.block.header.number)
            raise
        finally:
            self.tracer.finish_block(root)

    def submit_many(self, blocks) -> list:
        """Feed several height-ordered blocks, coalescing their verify
        dispatches in groups of ``coalesce_blocks`` (see the class
        docstring).  Returns the CommittedBlocks COMPLETED by these
        submissions — the in-flight tail stays in the pipe until the
        next submit or ``flush``.  Degrades to per-block ``submit``
        when coalescing is off, the pipe is serial, or the validator
        has no ``preprocess_many``."""
        blocks = list(blocks)
        self._apply_pending_knobs()
        k = self.coalesce_blocks
        if (self.depth == 1 or k < 2 or len(blocks) < 2
                or self._prefetch_many_fn is None):
            return [
                r for r in (self.submit(b) for b in blocks) if r is not None
            ]
        if self._closed:
            raise RuntimeError("pipeline is closed")
        try:
            return self._submit_many_coalesced(blocks, k)
        except BaseException:
            self._fail_closed()
            raise

    def _submit_many_coalesced(self, blocks, k) -> list:
        out = []
        i = 0
        while i < len(blocks):
            group = blocks[i:i + k]
            i += len(group)
            if len(group) == 1:
                r = self.submit(group[0])
                if r is not None:
                    out.append(r)
                continue
            # ONE prefetch-thread call stages every block in the group
            # and launches their signature batches as one coalesced
            # device dispatch; each block then takes the normal path
            # on its own slice of the device output.  The group's
            # prefetch span hangs off the LEADER's root; every member
            # root records its membership so /trace shows which blocks
            # shared the dispatch.
            lead = group[0].header.number
            roots = []
            for b in group:
                r = self.tracer.begin_block(b.header.number,
                                            channel=self.channel)
                self.tracer.set_attrs(r, coalesce_group=int(lead),
                                      coalesce_size=len(group))
                roots.append(r)
            fut = self._prefetch.submit(
                self._prefetch_many_traced, group, roots[0], len(group)
            )
            # barrier taint: the WHOLE group was staged just now, so a
            # barrier committing anywhere during this loop (an in-group
            # config/lifecycle block, or the previous group's tail
            # finishing at j=0) makes every REMAINING slice stale —
            # _finish_and_commit's flag only covers the immediate
            # successor, so latch it and force the per-block redo for
            # the rest of the group (barriers are rare; the serial
            # redo is the correctness price, same as per-block mode)
            stale_group = False
            for j, block in enumerate(group):
                t_sub = time.perf_counter()
                assert self._pre is None, (
                    "submit_many() before the previous returned"
                )
                self._pre = (block, _SliceFuture(fut, j), roots[j])
                self._inflight_gauge.set(self.inflight,
                                         channel=self.channel)
                res = None
                if self._launched is not None:
                    res = self._finish_and_commit(self._launched)
                if self._stale_prefetch:
                    stale_group = True
                elif stale_group:
                    self._stale_prefetch = True
                self._launch_next(
                    res.stage_s if res is not None else {}, t_sub
                )
                if res is not None:
                    out.append(res)
        return out

    def flush(self):
        """Drain: finish + commit the last launched block and wait for
        every committer-thread commit.  Returns the final
        CommittedBlock (or None if nothing was in flight).  A stage
        or commit exception fails the pipe closed and surfaces ONCE
        (see ``submit``)."""
        try:
            return self._flush_inner()
        except BaseException:
            self._fail_closed()
            raise

    def _flush_inner(self):
        out = None
        if self._launched is not None:
            out = self._finish_and_commit(self._launched, tail=True)
            self._launched = None
        if self._pre is not None:
            # a prefetched block with no successor: run it serially
            block, fut, root = self._pre
            self._pre = None
            try:
                pre = _wait_result(fut, "prefetch", self.channel)
                if self._stale_prefetch:
                    # prefetched before its barrier predecessor
                    # committed
                    self._stale_prefetch = False
                    self.tracer.event("stale_prefetch_reparse",
                                      parent=root)
                    with self.tracer.span("re-prefetch", parent=root):
                        pre = self.prefetch_fn(block)
            except BaseException:
                self._note_stage_failure("prefetch", block.header.number)
                raise
            try:
                with self.tracer.span("launch", parent=root) as lsp:
                    _faults.fire("pipeline.launch")
                    if self.pre_launch_fn is not None:
                        self.pre_launch_fn(block)
                    overlay, extra = self._launch_overlay()
                    t0 = time.perf_counter()
                    pend = self.validator.validate_launch(
                        block, pre=pre, overlay=overlay,
                        extra_txids=extra,
                    )
                    self._launch_s = time.perf_counter() - t0
                    self.tracer.set_attrs(
                        lsp, device=getattr(pend, "fetch2", None)
                        is not None,
                    )
            except BaseException:
                self._note_stage_failure("launch", block.header.number)
                raise
            self._launched_root = root
            out = self._finish_and_commit(pend, tail=True)
        # records are popped before waiting inside the drain: a commit
        # error must surface exactly once, not re-raise at close()
        self._drain_commits(0)
        # nothing is prefetched past this point: a barrier flushed as
        # the tail must not make the NEXT submit discard and redo its
        # (post-barrier) prefetch
        self._stale_prefetch = False
        self._inflight_gauge.set(0, channel=self.channel)
        return out

    def _submit_serial(self, block) -> CommittedBlock:
        tr = self.tracer
        root = tr.begin_block(block.header.number, channel=self.channel,
                              mode="serial")
        t0 = time.perf_counter()
        stage = "launch"  # failure label tracks the stage under way
        try:
            with tr.span("launch", parent=root):
                _faults.fire("pipeline.launch")
                if self.pre_launch_fn is not None:
                    self.pre_launch_fn(block)
                with tr.span("prefetch"):  # inline in serial mode
                    stage = "prefetch"
                    _faults.fire("pipeline.prefetch")
                    pre = self.prefetch_fn(block)
                    stage = "launch"
                pend = self.validator.validate_launch(block, pre=pre)
            stage = "finish"
            with tr.span("finish", parent=root):
                flt, batch, history = self.validator.validate_finish(pend)
        except BaseException:
            self._note_stage_failure(stage, block.header.number)
            raise
        t1 = time.perf_counter()
        res = CommittedBlock(
            block=block, pend=pend, tx_filter=flt, batch=batch,
            history=history, barrier=_is_barrier(pend, batch),
            stage_s={"finish": t1 - t0}, root_span=root,
        )
        try:
            with tr.span("commit", parent=root):
                _faults.fire("pipeline.commit")
                self._run_commit(res)
        except BaseException:
            self._note_stage_failure("commit", block.header.number)
            raise
        finally:
            tr.finish_block(root)
        res.stage_s["commit_wait"] = time.perf_counter() - t1
        self._blocks_ctr.add(1, channel=self.channel, mode="serial")
        return res

    def _finish_and_commit(self, pend, tail: bool = False):
        """Sync the device for ``pend``, serialize behind enough of the
        in-flight commit window (all of it for barriers/tails; enough
        to keep at most depth−1 commits in flight otherwise), then
        either commit inline (barrier/tail) or hand the commit to the
        committer thread and join the batch to the successors' merged
        overlay window."""
        root = self._launched_root
        self._launched_root = None
        t0 = time.perf_counter()
        try:
            with self.tracer.span("finish", parent=root):
                flt, batch, history = self.validator.validate_finish(pend)
        except BaseException:
            self._note_stage_failure("finish", pend.block.header.number)
            raise
        t1 = time.perf_counter()
        barrier = _is_barrier(pend, batch)
        # depth 2: wait THE predecessor commit (the classic overlap);
        # depth N: only block once N−1 commits are already in flight —
        # a slow fsync deep in the window no longer stalls this launch
        self._drain_commits(
            0 if (barrier or tail) else max(0, self.depth - 2)
        )
        t2 = time.perf_counter()
        self.tracer.add("commit_wait", t1, t2, parent=root)
        res = CommittedBlock(
            block=pend.block, pend=pend, tx_filter=flt, batch=batch,
            history=history, barrier=barrier,
            # fsync deferral is a DEPTH ≥ 3 behavior: at the default
            # depth 2 every commit keeps the classic forced per-block
            # fsync, so acknowledged-durability semantics are exactly
            # the pre-depth-N ones on unchanged configs
            defer_sync=self.depth >= 3 and not (barrier or tail),
            stage_s={"launch": self._launch_s, "finish": t1 - t0,
                     "commit_wait": t2 - t1},
            root_span=root,
        )
        self._launch_s = 0.0
        self._stage_hist.observe(t1 - t0, channel=self.channel,
                                 stage="finish")
        self._stage_hist.observe(t2 - t1, channel=self.channel,
                                 stage="commit_wait")
        if barrier or tail:
            # barrier: rotated validation inputs must be fully
            # committed (and the overlay window dropped) before any
            # launch; tail: nothing left to overlap with.  Either way
            # the fsync window closes here (defer_sync=False).
            self.tracer.set_attrs(
                root, **({"barrier": True} if barrier else {"tail": True})
            )
            try:
                with self.tracer.span("commit", parent=root):
                    _faults.fire("pipeline.commit")
                    self._run_commit(res)
            except BaseException:
                self._note_stage_failure(
                    "commit", res.block.header.number
                )
                raise
            finally:
                self.tracer.finish_block(root)
            if barrier:
                self._stale_prefetch = True
        else:
            fut = self._committer.submit(self._commit_traced, res, root)
            self._commits.append(_InflightCommit(
                fut=fut, batch=batch, txids=pend.txids,
                number=pend.block.header.number,
            ))
        self._blocks_ctr.add(
            1, channel=self.channel,
            mode="barrier" if barrier else "pipelined",
        )
        self._launched = None
        return res

    def _launch_next(self, prev_stage_s: dict, t_sub: float) -> None:
        block, fut, root = self._pre
        self._pre = None
        t0 = time.perf_counter()
        try:
            # host parse ran while the device synced
            pre = _wait_result(fut, "prefetch", self.channel)
            if self._stale_prefetch:
                # this block was staged on the prefetch thread BEFORE
                # its barrier predecessor committed, so its parse/
                # policy plans saw pre-barrier state — and
                # validate_launch's staleness backstop is an identity
                # check that state-backed policy providers (lifecycle
                # caches rotate IN PLACE) never trip.  Redo the parse
                # against post-barrier state; barriers are rare, the
                # serial redo is the correctness price.
                self._stale_prefetch = False
                self.tracer.event("stale_prefetch_reparse", parent=root)
                with self.tracer.span("re-prefetch", parent=root):
                    pre = self.prefetch_fn(block)
        except BaseException:
            self._note_stage_failure("prefetch", block.header.number)
            raise
        t1 = time.perf_counter()
        self.tracer.add("prefetch_wait", t0, t1, parent=root)
        try:
            with self.tracer.span("launch", parent=root) as lsp:
                _faults.fire("pipeline.launch")
                if self.pre_launch_fn is not None:
                    # caller thread, AFTER any predecessor barrier
                    # flushed — the node verifies orderer block
                    # signatures here against the post-rotation bundle
                    self.pre_launch_fn(block)
                overlay, extra = self._launch_overlay()
                self._launched = self.validator.validate_launch(
                    block, pre=pre, overlay=overlay,
                    extra_txids=extra,
                )
                # attribution aid for /trace + the device ledger's
                # exemplars: a block silently riding the host path
                # (no fused stage-2 dispatch) must be visible
                self.tracer.set_attrs(
                    lsp, device=getattr(self._launched, "fetch2", None)
                    is not None,
                )
        except BaseException:
            self._note_stage_failure("launch", block.header.number)
            raise
        self._launched_root = root
        t2 = time.perf_counter()
        self._launch_s = t2 - t1
        self._inflight_gauge.set(self.inflight, channel=self.channel)
        self._stage_hist.observe(t1 - t0, channel=self.channel,
                                 stage="prefetch_wait")
        self._stage_hist.observe(t2 - t1, channel=self.channel,
                                 stage="launch")
        total = t2 - t_sub
        if prev_stage_s and total > 0:
            blocked = (t1 - t0) + prev_stage_s.get("commit_wait", 0.0)
            self._overlap_hist.observe(
                max(0.0, 1.0 - blocked / total), channel=self.channel
            )

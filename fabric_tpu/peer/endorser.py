"""Endorser: ProcessProposal — simulate a proposal and sign the result.

Analog of core/endorser/endorser.go:304-476: unpack + auth the signed
proposal, run the chaincode against a tx simulator, wrap the rwset in
a ProposalResponsePayload whose hash binds (proposal, results), and
sign prp‖endorser with the peer's signing identity (the default ESCC,
core/handlers/endorsement/builtin/default_endorsement.go:35).  The
signature bytes produced here are EXACTLY what the TPU batch kernel
verifies at commit (validator_keylevel.go:244-260 SignedData layout —
see fabric_tpu.peer.txassembly.create_proposal_response)."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from fabric_tpu import protoutil
from fabric_tpu.peer.chaincode import ChaincodeError, ChaincodeRuntime
from fabric_tpu.peer.signlane import SignBusy
from fabric_tpu.peer.simulator import TxSimulator
from fabric_tpu.protos import common_pb2, proposal_pb2


@dataclass
class EndorseResult:
    response: proposal_pb2.ProposalResponse
    pvt_cleartext: dict = field(default_factory=dict)
    tx_id: str = ""


class Endorser:
    def __init__(self, msp_manager, signer, state_db,
                 runtime: ChaincodeRuntime, acl_check=None):
        """signer: the peer's ESCC signing PROVIDER — a
        SigningIdentity, or a signlane.BatchedSigner routing ``sign``
        through the device-batched sign lane (same ``sign`` +
        ``serialized`` surface; a provider answering SignBusy maps to
        a 429 proposal response below).
        acl_check(channel, creator_bytes, message, signature) -> bool
        (the peer/Propose Writers-policy gate, aclmgmt)."""
        self.msp = msp_manager
        self.signer = signer
        self.state = state_db
        self.runtime = runtime
        self.acl_check = acl_check

    def process_proposal(self, signed: proposal_pb2.SignedProposal) -> EndorseResult:
        prop = protoutil.unmarshal(proposal_pb2.Proposal, signed.proposal_bytes)
        header = protoutil.unmarshal(common_pb2.Header, prop.header)
        ch = protoutil.unmarshal(common_pb2.ChannelHeader, header.channel_header)
        sh = protoutil.unmarshal(common_pb2.SignatureHeader, header.signature_header)

        # auth: creator identity valid + signature over proposal bytes
        # (endorser.go:315-339 preProcess → validateSignedProposal)
        ident = self.msp.deserialize_identity(sh.creator)
        if not ident.is_valid:
            return self._err(500, "invalid creator identity")
        if not ident.verify(signed.proposal_bytes, signed.signature):
            return self._err(500, "invalid proposal signature")
        if ch.tx_id != protoutil.compute_tx_id(sh.nonce, sh.creator):
            return self._err(500, "tx_id mismatch")
        if self.acl_check is not None and not self.acl_check(
            ch.channel_id, sh.creator, signed.proposal_bytes, signed.signature
        ):
            return self._err(403, "access denied")

        # what to run
        cpp = protoutil.unmarshal(
            proposal_pb2.ChaincodeProposalPayload, prop.payload
        )
        spec = protoutil.unmarshal(
            proposal_pb2.ChaincodeInvocationSpec, cpp.input
        )
        cc_name = spec.chaincode_spec.chaincode_id.name
        args = list(spec.chaincode_spec.input.args)
        transient = dict(cpp.TransientMap)

        # simulate (endorser.go:379-401 GetTxSimulator + simulateProposal)
        sim = TxSimulator(self.state)
        try:
            resp = self.runtime.execute(
                sim, cc_name, args, transient=transient, creator=sh.creator,
                channel=ch.channel_id,
            )
        except ChaincodeError as e:
            return self._err(500, str(e))
        if resp.status >= 400:
            # failed simulation is NOT endorsed (no rwset leaves the peer)
            return self._err(resp.status, resp.message)
        rwset_bytes, pvt_clear = sim.done()

        events = b""
        ev_list = getattr(resp, "events", [])
        if ev_list:
            name, payload = ev_list[-1]  # one event per tx, like the shim
            events = proposal_pb2.ChaincodeEvent(
                chaincode_id=cc_name, tx_id=ch.tx_id,
                event_name=name, payload=payload,
            ).SerializeToString()

        # assemble + ESCC-sign
        from fabric_tpu.peer import txassembly as txa

        try:
            pr = txa.create_proposal_response(
                prop, rwset_bytes, self.signer, cc_name,
                response_payload=resp.payload, events=events,
                status=resp.status,
            )
        except SignBusy as e:
            # typed overflow from a full sign batcher: the simulation
            # ran but no signature leaves — 429 tells the client (and
            # the gateway layout loop) to back off and retry
            return self._err(429, str(e))
        return EndorseResult(response=pr, pvt_cleartext=pvt_clear, tx_id=ch.tx_id)

    @staticmethod
    def _err(status: int, msg: str) -> EndorseResult:
        pr = proposal_pb2.ProposalResponse()
        pr.response.status = status
        pr.response.message = msg
        return EndorseResult(response=pr)


def proposal_digest(signed: proposal_pb2.SignedProposal) -> bytes:
    return hashlib.sha256(signed.proposal_bytes).digest()

"""Chaincode lifecycle: the ``_lifecycle`` namespace as a system
contract + state-backed validation info for the plugin dispatcher.

Reference: core/chaincode/lifecycle (ExternalFunctions, the
``_lifecycle`` SCC, the cache feeding GetInfoForValidate —
plugindispatcher/dispatcher.go:266).  A chaincode definition is
agreed by approve/commit transactions whose writes land in the
``_lifecycle`` namespace of the SAME ledger the definitions govern, so
changing a chaincode's endorsement policy is itself an ordered,
validated, replayable transaction — and validation info for namespace
N is always read from committed state, never from node-local config.

Definition encoding: JSON (one state key per definition) rather than
the reference's per-field proto keys — the wire contract that matters
(rwset bytes) is unchanged.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from fabric_tpu.crypto.msp import policy_from_proto
from fabric_tpu.peer.chaincode import ChaincodeError, Contract, Response
from fabric_tpu.peer.validator import NamespaceInfo
from fabric_tpu.protos import common_pb2, policies_pb2

LIFECYCLE_NS = "_lifecycle"


def definition_key(name: str) -> str:
    return f"namespaces/fields/{name}/Definition"


def approval_key(name: str, sequence: int, msp_id: str) -> str:
    return f"namespaces/approvals/{name}/{sequence}/{msp_id}"


@dataclass
class ChaincodeDefinition:
    """One committed chaincode definition (the dispatcher's
    GetInfoForValidate payload)."""

    name: str
    sequence: int
    plugin: str = "default"
    # policy: {"sig": hex(SignaturePolicyEnvelope)} or
    #         {"ref": "<channel application policy name>"}
    policy: dict = field(default_factory=lambda: {"ref": "Endorsement"})
    init_required: bool = False
    # collections: {name: {"member_orgs": [msp_id...],
    #   "required_peer_count": int, "max_peer_count": int, "btl": int}}
    # — the StaticCollectionConfig surface (peer/collection.proto:
    # member_orgs_policy, required/maximum peer counts, block_to_live)
    collections: dict = field(default_factory=dict)

    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "name": self.name,
                "sequence": self.sequence,
                "plugin": self.plugin,
                "policy": self.policy,
                "init_required": self.init_required,
                "collections": self.collections,
            },
            sort_keys=True,
        ).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "ChaincodeDefinition":
        d = json.loads(raw)
        return cls(
            name=d["name"], sequence=int(d["sequence"]),
            plugin=d.get("plugin", "default"),
            policy=d.get("policy", {"ref": "Endorsement"}),
            init_required=bool(d.get("init_required", False)),
            collections=d.get("collections", {}),
        )


def policy_spec_from_ast(rule) -> dict:
    from fabric_tpu.crypto.msp import policy_to_proto

    return {"sig": policy_to_proto(rule).SerializeToString().hex()}


class LifecycleContract(Contract):
    """The ``_lifecycle`` system contract (approve / commit / query).

    ``org_lister`` returns the channel's application org MSP ids (from
    the channelconfig bundle) — commit requires approvals from a
    MAJORITY of them, the reference's default LifecycleEndorsement
    policy shape.
    """

    def __init__(self, org_lister=None):
        self.org_lister = org_lister or (lambda: [])

    @staticmethod
    def _creator_msp(stub) -> str:
        sid = common_pb2.SerializedIdentity()
        sid.ParseFromString(stub.creator)
        if not sid.mspid:
            raise ChaincodeError("no creator identity")
        return sid.mspid

    def approve(self, stub, name: bytes, sequence: bytes, spec: bytes = b"{}"):
        """ApproveChaincodeDefinitionForMyOrg: record this org's vote
        for (name, sequence, definition-hash)."""
        msp_id = self._creator_msp(stub)
        seq = int(sequence)
        cur = stub.get_state(definition_key(name.decode()))
        cur_seq = ChaincodeDefinition.from_bytes(cur).sequence if cur else 0
        if seq != cur_seq + 1:
            raise ChaincodeError(
                f"requested sequence {seq}, next committable is {cur_seq + 1}"
            )
        stub.put_state(
            approval_key(name.decode(), seq, msp_id),
            json.dumps(json.loads(spec or b"{}"), sort_keys=True).encode(),
        )
        return b"ok"

    def checkcommitreadiness(self, stub, name: bytes, sequence: bytes,
                             spec: bytes = b"{}"):
        ready = self._approvals(stub, name.decode(), int(sequence), spec)
        return json.dumps(ready, sort_keys=True).encode()

    @staticmethod
    def _norm_spec(raw: bytes) -> bytes:
        """Approval-comparison form: the package id is an ORG-LOCAL
        binding (which build this org runs), not part of the agreed
        definition — the reference likewise excludes packageID from
        the definition hash, so orgs running different builds of the
        same contract still converge."""
        d = json.loads(raw or b"{}")
        if not isinstance(d, dict):
            # a non-object approval can never normalize-match a real
            # spec; canonicalize without crashing commit for everyone
            return json.dumps(d, sort_keys=True).encode()
        d.pop("package_id", None)
        return json.dumps(d, sort_keys=True).encode()

    def _approvals(self, stub, name: str, seq: int, spec: bytes) -> dict:
        want = self._norm_spec(spec)
        out = {}
        for org in self.org_lister():
            got = stub.get_state(approval_key(name, seq, org))
            out[org] = got is not None and self._norm_spec(got) == want
        return out

    def commit(self, stub, name: bytes, sequence: bytes, spec: bytes = b"{}"):
        """CommitChaincodeDefinition: majority of orgs must have
        approved the identical definition at this sequence."""
        nm, seq = name.decode(), int(sequence)
        cur = stub.get_state(definition_key(nm))
        cur_seq = ChaincodeDefinition.from_bytes(cur).sequence if cur else 0
        if seq != cur_seq + 1:
            raise ChaincodeError(
                f"requested sequence {seq}, next committable is {cur_seq + 1}"
            )
        ready = self._approvals(stub, nm, seq, spec)
        approved = sum(1 for ok in ready.values() if ok)
        if not ready or approved < len(ready) // 2 + 1:
            raise ChaincodeError(
                f"insufficient approvals: {approved}/{len(ready)}"
            )
        params = json.loads(spec or b"{}")
        policy = params.get("policy", {"ref": "Endorsement"})
        cd = ChaincodeDefinition(
            name=nm, sequence=seq, plugin=params.get("plugin", "default"),
            policy=policy, init_required=bool(params.get("init_required")),
            collections=params.get("collections", {}),
        )
        stub.put_state(definition_key(nm), cd.to_bytes())
        stub.set_event("CommitChaincodeDefinition", nm.encode())
        return b"ok"

    def querydef(self, stub, name: bytes):
        raw = stub.get_state(definition_key(name.decode()))
        if raw is None:
            return Response(404, message=f"namespace {name.decode()} not defined")
        return raw


class LifecyclePolicyProvider:
    """PolicyProvider reading validation info from committed
    ``_lifecycle`` state (GetInfoForValidate,
    plugindispatcher/dispatcher.go:244-263), with the cache the
    reference keeps in lifecycle.Cache — invalidated when a committed
    block writes the ``_lifecycle`` namespace.

    ``ref_resolver(name)`` resolves channel-config policy references
    ("Endorsement", "LifecycleEndorsement") to policy ASTs — backed by
    the live channelconfig Bundle.
    """

    def __init__(self, state_db, ref_resolver=None, lifecycle_policy=None,
                 static_infos: dict | None = None):
        self.state = state_db
        self.ref_resolver = ref_resolver
        self.lifecycle_policy = lifecycle_policy
        self.static = dict(static_infos or {})
        self._cache: dict[str, NamespaceInfo | None] = {}

    def info(self, namespace: str) -> NamespaceInfo | None:
        if namespace in self._cache:
            return self._cache[namespace]
        got = self._load(namespace)
        self._cache[namespace] = got
        return got

    def _load(self, namespace: str) -> NamespaceInfo | None:
        if namespace == LIFECYCLE_NS:
            pol_ast = self.lifecycle_policy
            if pol_ast is None and self.ref_resolver is not None:
                pol_ast = self.ref_resolver("LifecycleEndorsement")
            return NamespaceInfo(policy=pol_ast) if pol_ast is not None else None
        vv = self.state.get_state(LIFECYCLE_NS, definition_key(namespace))
        if vv is None:
            return self.static.get(namespace)
        cd = ChaincodeDefinition.from_bytes(vv.value)
        ast = self._resolve_policy(cd.policy)
        if ast is None:
            return None
        return NamespaceInfo(policy=ast, plugin=cd.plugin)

    def collection(self, namespace: str, coll: str) -> dict | None:
        """Collection config from the committed definition (the
        distributor/coordinator's eligibility + BTL source,
        gossip/privdata/distributor.go:180-235) or None if the
        namespace/collection is undefined."""
        vv = self.state.get_state(LIFECYCLE_NS, definition_key(namespace))
        if vv is None:
            return None
        try:
            return ChaincodeDefinition.from_bytes(vv.value).collections.get(coll)
        except Exception:
            return None

    def _resolve_policy(self, spec: dict):
        if "sig" in spec:
            env = policies_pb2.SignaturePolicyEnvelope()
            env.ParseFromString(bytes.fromhex(spec["sig"]))
            return policy_from_proto(env)
        if "ref" in spec and self.ref_resolver is not None:
            return self.ref_resolver(spec["ref"])
        return None

    # -- commit hook -------------------------------------------------------

    def on_block_committed(self, batch) -> None:
        """Invalidate cached infos for namespaces whose definitions the
        block touched (batch: ledger.statedb.UpdateBatch)."""
        for (ns, _key), _vv in batch.items():
            if ns == LIFECYCLE_NS:
                self._cache.clear()
                return

"""The block validator: TPU-batched equivalent of the reference's
commit-path validation (the north-star component).

Reference shape (SURVEY §3.2): TxValidator v20 runs a goroutine per tx
(core/committer/txvalidator/v20/validator.go:180-265) doing envelope
checks + creator ECDSA verify, dup-txid, then the plugin dispatcher
walks each namespace's validation plugin which verifies every
endorsement signature inside the policy tree
(statebased/validator_keylevel.go:244-260, cauthdsl.go:24-110); the
ledger then runs a serial MVCC loop (validation/validator.go:81-118).

TPU-first re-ordering — compute first, control flow after:

  phase 0 (host)  parse every envelope, collect EVERY signature in the
                  block — creator sigs and endorsement sigs alike — as
                  (digest, r, s, qx, qy) tuples; bulk-load committed
                  versions for every read key.
  phase 1 (TPU)   ONE batched ECDSA verify over all signatures
                  (ops.p256), ONE vectorized policy reduction per
                  distinct policy shape (ops.policy_eval).
  phase 2 (TPU)   ONE MVCC kernel call over the whole block (ops.mvcc)
                  with pre_ok = structural ∧ creator-sig ∧ policy.
  phase 3 (host)  TRANSACTIONS_FILTER codes, update batch, history
                  writes for the ledger.

The plugin SPI (``ValidationPlugin``) keeps the reference's pluggable
boundary (core/handlers/validation/api/validation.go:26-38): the
built-in ``DefaultValidation`` implements phase-1 policy logic; custom
plugins get the same per-namespace dispatch
(plugindispatcher/dispatcher.go:102-221).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

from fabric_tpu import protoutil
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.identity import Identity, sig_to_ints
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import UpdateBatch
from fabric_tpu.ops import mvcc as mvcc_ops
from fabric_tpu.ops import p256
from fabric_tpu.protos import common_pb2, configtx_pb2, transaction_pb2

C = transaction_pb2.TxValidationCode


class ValidationPlugin:
    """SPI mirroring validation.Plugin (api/validation.go:26-38), but
    batch-shaped: given per-tx endorsement validity bits + identities,
    decide policy satisfaction for every tx at once."""

    def validate_batch(self, ctx: "BlockValidationCtx") -> np.ndarray:
        """→ [T] bool policy-ok for txs this plugin owns."""
        raise NotImplementedError


@dataclass
class NamespaceInfo:
    """Validation info for one namespace (the dispatcher's
    GetInfoForValidate analog, plugindispatcher/dispatcher.go:244-263)."""

    policy: object  # crypto.policy AST
    plugin: str = "default"


class PolicyProvider:
    """namespace → NamespaceInfo; backed by the lifecycle cache once
    chaincode lifecycle lands (reference: _lifecycle state)."""

    def __init__(self, infos: dict[str, NamespaceInfo], default: NamespaceInfo | None = None):
        self.infos = dict(infos)
        self.default = default

    def info(self, namespace: str) -> NamespaceInfo | None:
        return self.infos.get(namespace) or self.default


@dataclass
class ParsedTx:
    idx: int
    code: int = C.NOT_VALIDATED
    txid: str = ""
    channel: str = ""
    creator: bytes = b""
    namespaces: tuple = ()
    rwset: TxRWSet | None = None
    endorsements: list = field(default_factory=list)  # (endorser_serialized, item)
    creator_item_idx: int = -1
    endo_item_idx: list = field(default_factory=list)
    is_config: bool = False

    @property
    def undetermined(self) -> bool:
        return self.code == C.NOT_VALIDATED


@dataclass
class BlockValidationCtx:
    txs: list
    sig_valid: np.ndarray  # [n_items] bool, global signature batch
    msp_manager: object
    policy_provider: PolicyProvider


class BlockValidator:
    """Validate(block) → (tx_filter, UpdateBatch, history_writes)."""

    def __init__(
        self,
        msp_manager,
        policy_provider: PolicyProvider,
        state_db,
        block_store=None,
        plugins: dict[str, ValidationPlugin] | None = None,
        config_processor=None,
    ):
        self.msp = msp_manager
        self.policies = policy_provider
        self.state = state_db
        self.blocks = block_store
        self.plugins = {"default": DefaultValidation(), **(plugins or {})}
        self.config_processor = config_processor

    def warmup(self, n_sigs: int = 16) -> None:
        """Compile (or load from the persistent cache) the signature
        kernel for the smallest batch bucket before serving traffic —
        first-block latency must not eat a cold compile."""
        from fabric_tpu.crypto import ec_ref

        k = ec_ref.SigningKey.generate()
        e = ec_ref.digest_int(b"warmup")
        r, s = k.sign_digest(e)
        p256.verify_host([(e, r, s, *k.public)] * n_sigs)

    # -- phase 0: parse + collect -----------------------------------------

    def _parse(self, block: common_pb2.Block) -> tuple[list, list]:
        txs: list[ParsedTx] = []
        items: list = []  # (digest, r, s, qx, qy)
        seen_txids: dict[str, int] = {}
        for i, env_bytes in enumerate(block.data.data):
            ptx = ParsedTx(idx=i)
            txs.append(ptx)
            if not env_bytes:
                ptx.code = C.NIL_ENVELOPE
                continue
            try:
                env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
                payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
                ch = protoutil.unmarshal(
                    common_pb2.ChannelHeader, payload.header.channel_header
                )
                sh = protoutil.unmarshal(
                    common_pb2.SignatureHeader, payload.header.signature_header
                )
            except Exception:
                ptx.code = C.BAD_PAYLOAD
                continue
            ptx.txid, ptx.channel, ptx.creator = ch.tx_id, ch.channel_id, sh.creator

            if ch.type == common_pb2.HeaderType.CONFIG:
                # config txs go to the config machinery, not the
                # endorsement pipeline (v20/validator.go:397-419): the
                # creator signature still rides the block's signature
                # batch; structure + policy checks happen in
                # _validate_config after phase 1a.
                ptx.is_config = True
                if block.header.number == 0:
                    continue  # genesis: trust anchor, no creator check
                try:
                    ident = self.msp.deserialize_identity(sh.creator)
                    if not ident.is_valid:
                        raise ValueError("invalid creator identity")
                    item = _sig_item(ident, env.payload, env.signature)
                except Exception:
                    ptx.code = C.BAD_CREATOR_SIGNATURE
                    continue
                ptx.creator_item_idx = len(items)
                items.append(item)
                continue
            if ch.type != common_pb2.HeaderType.ENDORSER_TRANSACTION:
                ptx.code = C.UNKNOWN_TX_TYPE
                continue
            # txid binding: tx_id must equal sha256(nonce ‖ creator) —
            # prevents txid squatting / DUPLICATE_TXID poisoning
            # (protoutil/proputils.go:362 CheckTxID)
            if not ch.tx_id or ch.tx_id != protoutil.compute_tx_id(
                sh.nonce, sh.creator
            ):
                ptx.code = C.BAD_PROPOSAL_TXID
                continue
            # dup txid: in-block + vs ledger (v20/validator.go:460-481)
            if ch.tx_id in seen_txids or (
                self.blocks is not None and self.blocks.tx_exists(ch.tx_id)
            ):
                ptx.code = C.DUPLICATE_TXID
                continue
            seen_txids[ch.tx_id] = i

            # creator: deserializable, valid cert, sig over payload
            try:
                ident = self.msp.deserialize_identity(sh.creator)
            except Exception:
                ptx.code = C.BAD_CREATOR_SIGNATURE
                continue
            if not ident.is_valid:
                ptx.code = C.BAD_CREATOR_SIGNATURE
                continue
            try:
                item = _sig_item(ident, env.payload, env.signature)
            except Exception:
                ptx.code = C.BAD_CREATOR_SIGNATURE
                continue
            ptx.creator_item_idx = len(items)
            items.append(item)

            # endorsements + rwset
            try:
                _, _, cap, prp, cca = protoutil.extract_action(env)
                ptx.rwset = TxRWSet.from_bytes(cca.results)
                ptx.namespaces = tuple(sorted(ptx.rwset.ns))
                prp_bytes = cap.action.proposal_response_payload
                seen_endorsers: set[bytes] = set()
                for e in cap.action.endorsements:
                    # dedup by identity: a repeated endorser counts as
                    # ONE signature toward the policy (reference
                    # SignatureSetToValidIdentities,
                    # common/policies/policy.go:360-363)
                    if e.endorser in seen_endorsers:
                        continue
                    try:
                        eident = self.msp.deserialize_identity(e.endorser)
                        eitem = _sig_item(eident, prp_bytes + e.endorser, e.signature)
                    except Exception:
                        continue  # unparseable endorsement: contributes nothing
                    seen_endorsers.add(e.endorser)
                    ptx.endo_item_idx.append(len(items))
                    ptx.endorsements.append((e.endorser, eident))
                    items.append(eitem)
            except protoutil.TxParseError as e:
                ptx.code = e.code
                continue
            except Exception:
                ptx.code = C.BAD_RWSET
                continue
        return txs, items

    # -- the pipeline ------------------------------------------------------

    def validate(self, block: common_pb2.Block):
        txs, items = self._parse(block)
        # parsed records for post-commit consumers (config rotation) —
        # the commit path is serialized per channel, so this is safe
        self.last_parsed = txs

        # phase 1a: one batched ECDSA verify for the whole block
        sig_valid = np.asarray(p256.verify_host(items), bool) if items else np.zeros(0, bool)

        for ptx in txs:
            if ptx.undetermined and ptx.creator_item_idx >= 0:
                if not sig_valid[ptx.creator_item_idx]:
                    ptx.code = C.BAD_CREATOR_SIGNATURE

        # config txs: structural + signature + config-machinery checks
        # (v20/validator.go:397-419 — never rubber-stamped)
        for ptx in txs:
            if ptx.is_config and ptx.undetermined:
                ptx.code = self._validate_config(block, ptx)

        # phase 1b: per-namespace plugin dispatch (policy reduction).
        # A tx is valid only if EVERY written namespace's plugin
        # approves it (plugindispatcher/dispatcher.go:190-217).
        ctx = BlockValidationCtx(
            txs=txs, sig_valid=sig_valid, msp_manager=self.msp,
            policy_provider=self.policies,
        )
        by_plugin: dict[str, list[tuple[ParsedTx, tuple]]] = {}
        for ptx in txs:
            if not ptx.undetermined or ptx.is_config:
                continue
            infos = [self.policies.info(ns) for ns in ptx.namespaces]
            if not ptx.namespaces or any(i is None for i in infos):
                ptx.code = C.INVALID_CHAINCODE
                continue
            for ns, info in zip(ptx.namespaces, infos):
                name = info.plugin or "default"
                by_plugin.setdefault(name, []).append((ptx, ns))
        for name, group in by_plugin.items():
            plug = self.plugins.get(name)
            if plug is None:
                for ptx, _ in group:
                    ptx.code = C.INVALID_OTHER_REASON
                continue
            if hasattr(plug, "validate_batch_group"):
                ok = plug.validate_batch_group(ctx, group)
            else:
                # legacy SPI returns [T] per-tx verdicts; realign to the
                # per-(tx, namespace) group entries by block position
                per_tx = plug.validate_batch(ctx)
                ok = [per_tx[ptx.idx] for ptx, _ in group]
            for (ptx, _), good in zip(group, ok):
                if not good and ptx.undetermined:
                    ptx.code = C.ENDORSEMENT_POLICY_FAILURE

        # phase 2: MVCC over the whole block
        mvcc_txs, committed = self._mvcc_inputs(txs)
        pre_ok = np.array([ptx.undetermined for ptx in txs], bool)
        if txs:
            valid, conflict, phantom = mvcc_ops.mvcc_validate_block(
                mvcc_txs, committed, pre_ok
            )
            for ptx, v, ph in zip(txs, valid, phantom):
                if not ptx.undetermined:
                    continue
                if v:
                    ptx.code = C.VALID
                else:
                    ptx.code = C.PHANTOM_READ_CONFLICT if ph else C.MVCC_READ_CONFLICT

        # phase 3: filter + update batch + history
        tx_filter = bytes(ptx.code for ptx in txs)
        batch, history = self._build_updates(block.header.number, txs)
        return tx_filter, batch, history

    def _mvcc_inputs(self, txs):
        mvcc_txs = []
        all_read_keys = set()
        for ptx in txs:
            if ptx.rwset is None or not ptx.undetermined:
                mvcc_txs.append(mvcc_ops.TxRWSet(reads=[], writes=[], range_reads=[]))
                continue
            # re-execute range queries against COMMITTED state: a key
            # committed after simulation but inside the range is a
            # phantom even with no in-block writer (the reference
            # merges committed state into the range re-check,
            # validation/validator.go:205-247, combined_iterator.go:44).
            # Per-result version staleness rides the normal read checks;
            # in-block writers ride the id-interval kernel check.
            if self._committed_range_phantom(ptx):
                ptx.code = C.PHANTOM_READ_CONFLICT
                mvcc_txs.append(mvcc_ops.TxRWSet(reads=[], writes=[], range_reads=[]))
                continue
            reads, writes, rqs = ptx.rwset.mvcc_form()
            mvcc_txs.append(
                mvcc_ops.TxRWSet(reads=reads, writes=writes, range_reads=rqs)
            )
            all_read_keys.update(k for k, _ in reads)
        committed = {}
        if all_read_keys:
            pub_keys = [
                (k[1], k[2]) for k in all_read_keys if k[0] == "pub"
            ]
            vers = self.state.get_versions_bulk(pub_keys)
            for k in all_read_keys:
                if k[0] == "pub" and (k[1], k[2]) in vers:
                    committed[k] = vers[(k[1], k[2])]
                elif k[0] == "pvt":
                    v = self.state.get_version(f"{k[1]}${k[2]}#hashed", _hex(k[3]))
                    if v is not None:
                        committed[k] = v
        return mvcc_txs, committed

    def _committed_range_phantom(self, ptx) -> bool:
        """True iff some committed key falls inside a recorded range
        query but is missing from its recorded results (end_key == ''
        means unbounded, per the reference's open-ended iterators)."""
        for ns_name, n in ptx.rwset.ns.items():
            for start, end, results in n.range_queries:
                recorded = {k for k, _ in results}
                for key, _vv in self.state.get_state_range(ns_name, start, end):
                    if key not in recorded:
                        return True
        return False

    def _validate_config(self, block, ptx) -> int:
        """Config-tx validation: structure must parse as a
        ConfigEnvelope and the configured processor must accept it —
        CONFIG envelopes are never rubber-stamped
        (v20/validator.go:397-419)."""
        try:
            env = protoutil.unmarshal(common_pb2.Envelope, block.data.data[ptx.idx])
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            cfg_env = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
        except Exception:
            return C.BAD_PAYLOAD
        if block.header.number == 0:
            # genesis config is the channel's trust anchor — verified
            # out-of-band by the joining admin, not by prior state
            return C.VALID
        if self.config_processor is not None:
            try:
                return self.config_processor.validate_config_tx(ptx, cfg_env)
            except Exception:
                return C.INVALID_OTHER_REASON
        return C.VALID

    def _build_updates(self, block_num: int, txs):
        batch = UpdateBatch()
        history = []
        for ptx in txs:
            if ptx.code != C.VALID or ptx.rwset is None:
                continue
            ver = (block_num, ptx.idx)
            for ns_name in sorted(ptx.rwset.ns):
                n = ptx.rwset.ns[ns_name]
                for key in sorted(n.writes):
                    val = n.writes[key]
                    if val is None:
                        batch.delete(ns_name, key, ver)
                    else:
                        batch.put(ns_name, key, val, ver)
                    history.append((ns_name, key, ptx.idx))
                for coll in sorted(n.hashed):
                    hns = f"{ns_name}${coll}#hashed"
                    for kh, (vh, is_del) in sorted(n.hashed[coll].get("writes", {}).items()):
                        if is_del:
                            batch.delete(hns, _hex(kh), ver)
                        else:
                            batch.put(hns, _hex(kh), vh, ver)
        return batch, history


class DefaultValidation(ValidationPlugin):
    """Built-in plugin (analog builtin/default_validation.go +
    v20/validation_logic.go): evaluate one (tx, namespace) pair's
    chaincode policy over the tx's verified endorsements.  Plans are
    compiled once per policy object and cached (the reference caches
    per plugin^channel, plugin_validator.go)."""

    def __init__(self):
        # keyed by the (frozen, hashable) policy AST itself — id()-keys
        # could alias a recycled address after a config update GCs the
        # old policy object
        self._plan_cache: dict[object, pol.BatchPlan] = {}

    def _plan(self, policy) -> pol.BatchPlan:
        plan = self._plan_cache.get(policy)
        if plan is None:
            plan = pol.compile_plan(policy)
            self._plan_cache[policy] = plan
        return plan

    def validate_batch_group(self, ctx: BlockValidationCtx, group):
        out = []
        for ptx, ns in group:
            info = ctx.policy_provider.info(ns)
            plan = self._plan(info.policy)
            idents = [ident for (_, ident) in ptx.endorsements]
            m = pol.match_matrix(idents, plan.principals)
            valid = np.array(
                [ctx.sig_valid[i] for i in ptx.endo_item_idx], bool
            )
            m = m & valid[:, None] if len(idents) else m
            if plan.consumption_safe(m):
                ok = plan.evaluate_counts(m)
            else:
                ok = pol.evaluate(info.policy, m)
            out.append(bool(ok))
        return out


def _sig_item(ident: Identity, message: bytes, der_sig: bytes):
    r, s = sig_to_ints(der_sig)
    qx, qy = ident.public_numbers
    return (int.from_bytes(hashlib.sha256(message).digest(), "big"), r, s, qx, qy)


def _hex(b: bytes) -> str:
    return b.hex()

"""The block validator: TPU-batched equivalent of the reference's
commit-path validation (the north-star component).

Reference shape (SURVEY §3.2): TxValidator v20 runs a goroutine per tx
(core/committer/txvalidator/v20/validator.go:180-265) doing envelope
checks + creator ECDSA verify, dup-txid, then the plugin dispatcher
walks each namespace's validation plugin which verifies every
endorsement signature inside the policy tree
(statebased/validator_keylevel.go:244-260, cauthdsl.go:24-110); the
ledger then runs a serial MVCC loop (validation/validator.go:81-118).

TPU-first re-ordering — compute first, control flow after:

  phase 0 (host)  parse every envelope, collect EVERY signature in the
                  block — creator sigs and endorsement sigs alike — as
                  (digest, r, s, qx, qy) tuples; bulk-load committed
                  versions for every read key.
  phase 1 (TPU)   ONE batched ECDSA verify over all signatures
                  (ops.p256), ONE vectorized policy reduction per
                  distinct policy shape (peer.device_block).
  phase 2 (TPU)   ONE MVCC kernel call over the whole block (ops.mvcc)
                  with pre_ok = structural ∧ creator-sig ∧ policy.
  phase 3 (host)  TRANSACTIONS_FILTER codes, update batch, history
                  writes for the ledger.

The plugin SPI (``ValidationPlugin``) keeps the reference's pluggable
boundary (core/handlers/validation/api/validation.go:26-38): the
built-in ``DefaultValidation`` implements phase-1 policy logic; custom
plugins get the same per-namespace dispatch
(plugindispatcher/dispatcher.go:102-221).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

from fabric_tpu import protoutil
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.identity import Identity, sig_to_ints
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import UpdateBatch
from fabric_tpu.ops import mvcc as mvcc_ops
from fabric_tpu.ops import p256
from fabric_tpu.protos import common_pb2, configtx_pb2, transaction_pb2

C = transaction_pb2.TxValidationCode


class ValidationPlugin:
    """SPI mirroring validation.Plugin (api/validation.go:26-38), but
    batch-shaped: given per-tx endorsement validity bits + identities,
    decide policy satisfaction for every tx at once."""

    def validate_batch(self, ctx: "BlockValidationCtx") -> np.ndarray:
        """→ [T] bool policy-ok for txs this plugin owns."""
        raise NotImplementedError


@dataclass
class NamespaceInfo:
    """Validation info for one namespace (the dispatcher's
    GetInfoForValidate analog, plugindispatcher/dispatcher.go:244-263)."""

    policy: object  # crypto.policy AST
    plugin: str = "default"


class PolicyProvider:
    """namespace → NamespaceInfo; backed by the lifecycle cache once
    chaincode lifecycle lands (reference: _lifecycle state)."""

    def __init__(self, infos: dict[str, NamespaceInfo], default: NamespaceInfo | None = None):
        self.infos = dict(infos)
        self.default = default

    def info(self, namespace: str) -> NamespaceInfo | None:
        return self.infos.get(namespace) or self.default


@dataclass
class ParsedTx:
    idx: int
    code: int = C.NOT_VALIDATED
    txid: str = ""
    channel: str = ""
    creator: bytes = b""
    namespaces: tuple = ()
    rwset: TxRWSet | None = None
    endorsements: list = field(default_factory=list)  # (endorser_serialized, item)
    creator_item_idx: int = -1
    endo_item_idx: list = field(default_factory=list)
    is_config: bool = False

    @property
    def undetermined(self) -> bool:
        return self.code == C.NOT_VALIDATED


@dataclass
class BlockValidationCtx:
    txs: list
    sig_valid: np.ndarray  # [n_items] bool, global signature batch
    msp_manager: object
    policy_provider: PolicyProvider


@dataclass
class _DevicePre:
    """State-independent device-path inputs built at preprocess time
    (prefetch thread): policy groups + static MVCC arrays.  `policies`
    pins the provider the plans were compiled against — validate()
    re-preprocesses if the channel config rotated in between."""

    groups: list          # [(plan, match [E,S,P], endo_idx [E,S], tx_of [E])]
    group_entries: list   # parallel: [(ptx, info), ...] per group
    static: object        # mvcc_ops.StaticBlock
    has_range: bool
    policies: object


class BlockValidator:
    """Validate(block) → (tx_filter, UpdateBatch, history_writes)."""

    def __init__(
        self,
        msp_manager,
        policy_provider: PolicyProvider,
        state_db,
        block_store=None,
        plugins: dict[str, ValidationPlugin] | None = None,
        config_processor=None,
    ):
        self.msp = msp_manager
        self.policies = policy_provider
        self.state = state_db
        self.blocks = block_store
        self.plugins = {"default": DefaultValidation(), **(plugins or {})}
        self.config_processor = config_processor
        self._device_pipeline = None
        # optional phase accumulator (seconds per phase, summed across
        # blocks) — the bench publishes it as the per-phase breakdown
        # artifact; None = no instrumentation overhead
        self.timings: dict | None = None

    def _t(self, key: str, t0: float) -> float:
        import time

        t1 = time.perf_counter()
        if self.timings is not None:
            self.timings[key] = self.timings.get(key, 0.0) + (t1 - t0)
        return t1

    def warmup(self, n_sigs: int = 16) -> None:
        """Compile (or load from the persistent cache) the signature
        kernel for the smallest batch bucket before serving traffic —
        first-block latency must not eat a cold compile."""
        from fabric_tpu.crypto import ec_ref

        k = ec_ref.SigningKey.generate()
        e = ec_ref.digest_int(b"warmup")
        r, s = k.sign_digest(e)
        p256.verify_host([(e, r, s, *k.public)] * n_sigs)

    # -- phase 0: parse + collect -----------------------------------------

    def _parse(self, block: common_pb2.Block) -> tuple[list, list]:
        """Parse every envelope + collect the signature batch.

        Fast path: the native C++ pre-parser (fabric_tpu.native) walks
        the whole block's wire format, hashes every message and splits
        every DER signature in ONE call; envelopes it cannot fully
        handle (config txs, malformed bytes) fall back to the Python
        path below, envelope by envelope — identical verdicts either
        way (tests/test_native_parse.py pins the equivalence)."""
        from fabric_tpu.ops.p256v3 import SigCollector

        txs: list[ParsedTx] = []
        items = SigCollector()  # column-form signature batch
        seen_txids: dict[str, int] = {}
        native = None
        if len(block.data.data) >= 16 and block.header.number != 0:
            try:
                from fabric_tpu.native import blockparse as nbp

                native = nbp.parse_envelopes(list(block.data.data))
            except Exception:
                native = None
        for i, env_bytes in enumerate(block.data.data):
            if native is not None and native.ok[i]:
                self._parse_fast(i, native, txs, items, seen_txids)
                continue
            ptx = ParsedTx(idx=i)
            txs.append(ptx)
            if not env_bytes:
                ptx.code = C.NIL_ENVELOPE
                continue
            try:
                env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
                payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
                ch = protoutil.unmarshal(
                    common_pb2.ChannelHeader, payload.header.channel_header
                )
                sh = protoutil.unmarshal(
                    common_pb2.SignatureHeader, payload.header.signature_header
                )
            except Exception:
                ptx.code = C.BAD_PAYLOAD
                continue
            ptx.txid, ptx.channel, ptx.creator = ch.tx_id, ch.channel_id, sh.creator

            if ch.type == common_pb2.HeaderType.CONFIG:
                # config txs go to the config machinery, not the
                # endorsement pipeline (v20/validator.go:397-419): the
                # creator signature still rides the block's signature
                # batch; structure + policy checks happen in
                # _validate_config after phase 1a.
                ptx.is_config = True
                if block.header.number == 0:
                    continue  # genesis: trust anchor, no creator check
                try:
                    ident = self.msp.deserialize_identity(sh.creator)
                    if not ident.is_valid:
                        raise ValueError("invalid creator identity")
                    item = _sig_item(ident, env.payload, env.signature)
                except Exception:
                    ptx.code = C.BAD_CREATOR_SIGNATURE
                    continue
                ptx.creator_item_idx = items.add_slow(item)
                continue
            if ch.type != common_pb2.HeaderType.ENDORSER_TRANSACTION:
                ptx.code = C.UNKNOWN_TX_TYPE
                continue
            # txid binding: tx_id must equal sha256(nonce ‖ creator) —
            # prevents txid squatting / DUPLICATE_TXID poisoning
            # (protoutil/proputils.go:362 CheckTxID)
            if not ch.tx_id or ch.tx_id != protoutil.compute_tx_id(
                sh.nonce, sh.creator
            ):
                ptx.code = C.BAD_PROPOSAL_TXID
                continue
            # dup txid in-block (v20/validator.go:460-481); the
            # vs-ledger check happens in validate() — preprocess() must
            # be runnable BEFORE the previous block commits (pipeline)
            if ch.tx_id in seen_txids:
                ptx.code = C.DUPLICATE_TXID
                continue
            seen_txids[ch.tx_id] = i

            # creator: deserializable, valid cert, sig over payload
            try:
                ident = self.msp.deserialize_identity(sh.creator)
            except Exception:
                ptx.code = C.BAD_CREATOR_SIGNATURE
                continue
            if not ident.is_valid:
                ptx.code = C.BAD_CREATOR_SIGNATURE
                continue
            try:
                item = _sig_item(ident, env.payload, env.signature)
            except Exception:
                ptx.code = C.BAD_CREATOR_SIGNATURE
                continue
            ptx.creator_item_idx = items.add_slow(item)

            # endorsements + rwset
            try:
                _, _, cap, prp, cca = protoutil.extract_action(
                    env, parsed=(payload, ch, sh)
                )
                ptx.rwset = TxRWSet.from_bytes(cca.results)
                ptx.namespaces = tuple(sorted(ptx.rwset.ns))
                prp_bytes = cap.action.proposal_response_payload
                seen_endorsers: set[bytes] = set()
                for e in cap.action.endorsements:
                    # dedup by identity: a repeated endorser counts as
                    # ONE signature toward the policy (reference
                    # SignatureSetToValidIdentities,
                    # common/policies/policy.go:360-363)
                    if e.endorser in seen_endorsers:
                        continue
                    try:
                        eident = self.msp.deserialize_identity(e.endorser)
                        eitem = _sig_item(eident, prp_bytes + e.endorser, e.signature)
                    except Exception:
                        continue  # unparseable endorsement: contributes nothing
                    seen_endorsers.add(e.endorser)
                    ptx.endo_item_idx.append(items.add_slow(eitem))
                    ptx.endorsements.append((e.endorser, eident))
            except protoutil.TxParseError as e:
                ptx.code = e.code
                continue
            except Exception:
                ptx.code = C.BAD_RWSET
                continue
        return txs, items

    def _parse_fast(self, i: int, native, txs, items, seen_txids) -> None:
        """Native-pre-parsed endorser tx → ParsedTx + signature items;
        check order mirrors the Python path exactly."""
        ptx = ParsedTx(idx=i)
        txs.append(ptx)
        txid_b = native.span(native.txid_span, i)
        channel_b = native.span(native.channel_span, i)
        creator = native.span(native.creator_span, i) or b""
        ptx.txid = txid_b.decode("utf-8", "replace") if txid_b else ""
        ptx.channel = channel_b.decode("utf-8", "replace") if channel_b else ""
        ptx.creator = creator

        # txid binding: tx_id == sha256(nonce ‖ creator) hex
        if not ptx.txid or ptx.txid != bytes(native.txid_digest[i]).hex():
            ptx.code = C.BAD_PROPOSAL_TXID
            return
        if ptx.txid in seen_txids:
            ptx.code = C.DUPLICATE_TXID
            return
        seen_txids[ptx.txid] = i

        try:
            ident = self.msp.deserialize_identity(creator)
            ident.public_numbers  # EC key required (raises otherwise)
        except Exception:
            ptx.code = C.BAD_CREATOR_SIGNATURE
            return
        if not ident.is_valid or not native.creator_sig_ok[i]:
            ptx.code = C.BAD_CREATOR_SIGNATURE
            return
        ptx.creator_item_idx = items.add_fast(
            (native.payload_digest, native.creator_r, native.creator_s),
            i, ident,
        )

        try:
            results = native.span(native.results_span, i) or b""
            ptx.rwset = TxRWSet.from_bytes(results)
            ptx.namespaces = tuple(sorted(ptx.rwset.ns))
        except Exception:
            ptx.code = C.BAD_RWSET
            return
        seen_endorsers: set[bytes] = set()
        base = int(native.endo_start[i])
        for j in range(base, base + int(native.endo_count[i])):
            endorser = native.span(native.e_endorser_span, j)
            if not native.e_ok[j] or endorser is None:
                continue  # unparseable endorsement contributes nothing
            if endorser in seen_endorsers:
                continue  # dedup by identity (policy.go:360-363)
            try:
                eident = self.msp.deserialize_identity(endorser)
                eident.public_numbers  # EC key required
            except Exception:
                continue
            seen_endorsers.add(endorser)
            ptx.endo_item_idx.append(items.add_fast(
                (native.e_digest, native.e_r, native.e_s), j, eident,
            ))
            ptx.endorsements.append((endorser, eident))

    # -- the pipeline ------------------------------------------------------

    def preprocess(self, block: common_pb2.Block):
        """Host parse + ASYNC device-verify launch + state-independent
        device-path inputs (policy match matrices, static MVCC arrays)
        for one block.

        Safe to run for block n+1 while block n is still committing
        (touches no ledger state): the peer's deliver loop and the
        bench overlap the host phase of the next block with the device
        phase of the current one — the TPU-shaped analog of the
        reference's deliver prefetch + validator pool overlap
        (gossip/state/state.go:540, v20/validator.go:193)."""
        import time

        t0 = time.perf_counter()
        txs, items = self._parse(block)
        t0 = self._t("host_parse", t0)
        fetch = p256.verify_launch(items)
        t0 = self._t("sig_prepare_launch", t0)
        dpre = self._device_preprocess(txs)
        self._t("device_pre", t0)
        # the MSP manager the identities were validated against: a
        # config tx in the PREVIOUS block may rotate membership between
        # preprocess and validate — validate() detects and re-parses
        return txs, items, fetch, self.msp, dpre

    def validate(self, block: common_pb2.Block, pre=None):
        if pre is None:
            pre = self.preprocess(block)
        if pre[3] is not self.msp or (
            pre[4] is not None and pre[4].policies is not self.policies
        ):
            # membership or policy tree rotated after this block was
            # preprocessed (committed config tx): stale identity
            # validations / plans must not leak — redo the parse
            pre = self.preprocess(block)
        txs, items, fetch, _, dpre = pre
        # parsed records for post-commit consumers (config rotation) —
        # the commit path is serialized per channel, so this is safe
        self.last_parsed = txs

        # dup txid vs committed ledger (deferred from preprocess)
        if self.blocks is not None:
            for ptx in txs:
                if (
                    ptx.undetermined and not ptx.is_config
                    and self.blocks.tx_exists(ptx.txid)
                ):
                    ptx.code = C.DUPLICATE_TXID

        # fused single-sync device path: policy + MVCC consume the
        # verify output ON DEVICE (one dispatch + one readback per
        # block); falls back to the host path for custom plugins,
        # non-v3 kernels, or consumption-unsafe blocks
        if getattr(fetch, "device_out", None) is not None and txs and dpre:
            result = self._validate_device(block, txs, items, fetch, dpre)
            if result is not None:
                return result

        return self._validate_host(block, txs, items, fetch)

    def _validate_host(self, block, txs, items, fetch):
        # phase 1a: one batched ECDSA verify for the whole block
        sig_valid = np.asarray(fetch(), bool) if items else np.zeros(0, bool)

        for ptx in txs:
            if ptx.undetermined and ptx.creator_item_idx >= 0:
                if not sig_valid[ptx.creator_item_idx]:
                    ptx.code = C.BAD_CREATOR_SIGNATURE

        # config txs: structural + signature + config-machinery checks
        # (v20/validator.go:397-419 — never rubber-stamped)
        for ptx in txs:
            if ptx.is_config and ptx.undetermined:
                ptx.code = self._validate_config(block, ptx)

        # phase 1b: per-namespace plugin dispatch (policy reduction).
        # A tx is valid only if EVERY written namespace's plugin
        # approves it (plugindispatcher/dispatcher.go:190-217).
        ctx = BlockValidationCtx(
            txs=txs, sig_valid=sig_valid, msp_manager=self.msp,
            policy_provider=self.policies,
        )
        by_plugin: dict[str, list[tuple[ParsedTx, tuple]]] = {}
        for ptx in txs:
            if not ptx.undetermined or ptx.is_config:
                continue
            infos = [self.policies.info(ns) for ns in ptx.namespaces]
            if not ptx.namespaces or any(i is None for i in infos):
                ptx.code = C.INVALID_CHAINCODE
                continue
            for ns, info in zip(ptx.namespaces, infos):
                name = info.plugin or "default"
                by_plugin.setdefault(name, []).append((ptx, ns))
        for name, group in by_plugin.items():
            plug = self.plugins.get(name)
            if plug is None:
                for ptx, _ in group:
                    ptx.code = C.INVALID_OTHER_REASON
                continue
            if hasattr(plug, "validate_batch_group"):
                ok = plug.validate_batch_group(ctx, group)
            else:
                # legacy SPI returns [T] per-tx verdicts; realign to the
                # per-(tx, namespace) group entries by block position
                per_tx = plug.validate_batch(ctx)
                ok = [per_tx[ptx.idx] for ptx, _ in group]
            for (ptx, _), good in zip(group, ok):
                if not good and ptx.undetermined:
                    ptx.code = C.ENDORSEMENT_POLICY_FAILURE

        # phase 2: MVCC over the whole block
        mvcc_txs, committed = self._mvcc_inputs(txs)
        pre_ok = np.array([ptx.undetermined for ptx in txs], bool)
        if txs:
            valid, conflict, phantom = mvcc_ops.mvcc_validate_block(
                mvcc_txs, committed, pre_ok
            )
            for ptx, v, ph in zip(txs, valid, phantom):
                if not ptx.undetermined:
                    continue
                if v:
                    ptx.code = C.VALID
                else:
                    ptx.code = C.PHANTOM_READ_CONFLICT if ph else C.MVCC_READ_CONFLICT

        # phase 3: filter + update batch + history
        tx_filter = bytes(ptx.code for ptx in txs)
        batch, history = self._build_updates(block.header.number, txs)
        return tx_filter, batch, history

    # -- fused single-sync device path ------------------------------------

    def _device_preprocess(self, txs):
        """State-INDEPENDENT device-path inputs: policy match matrices
        (vectorized gather over per-identity cached principal rows) and
        static MVCC arrays.  Runs in the prefetch thread, overlapping
        the previous block's device time; returns None when the block
        needs the host dispatch path (custom plugins)."""
        from fabric_tpu.ops import mvcc as mvcc_ops
        from fabric_tpu.utils.batching import next_pow2

        if not txs or p256._KERNEL in ("v1", "v2"):
            return None  # fused device path requires the v3 kernel
        default = self.plugins.get("default")
        if type(default).__name__ != "DefaultValidation":
            return None

        entries = []  # (ptx, ns, info)
        for ptx in txs:
            if not ptx.undetermined or ptx.is_config:
                continue
            infos = [self.policies.info(ns) for ns in ptx.namespaces]
            if not ptx.namespaces or any(i is None for i in infos):
                ptx.code = C.INVALID_CHAINCODE  # same verdict on both paths
                continue
            if any((i.plugin or "default") != "default" for i in infos):
                return None  # custom plugin in play → host dispatch path
            for ns, info in zip(ptx.namespaces, infos):
                entries.append((ptx, ns, info))

        # policy groups (by policy object), padded to buckets; match
        # rows built once per distinct identity then gathered
        by_policy: dict[int, list] = {}
        plans: dict[int, object] = {}
        for ptx, ns, info in entries:
            key = id(info.policy)
            if key not in plans:
                plans[key] = default._plan(info.policy)
            by_policy.setdefault(key, []).append((ptx, info))
        groups = []
        group_entries = []
        for key, ents in by_policy.items():
            plan = plans[key]
            P = len(plan.principals)
            S = max(4, next_pow2(max(
                (len(p.endorsements) for p, _ in ents), default=1) or 1))
            E = max(16, next_pow2(len(ents)))
            pool_rows = [np.zeros(P, bool)]  # row 0 = padding (no match)
            pool_of: dict[int, int] = {}
            idx_mat = np.zeros((E, S), np.int32)
            endo_idx = np.full((E, S), -1, np.int32)
            tx_of = np.full(E, -1, np.int32)
            for e, (ptx, info) in enumerate(ents):
                tx_of[e] = ptx.idx
                if ptx.endo_item_idx:
                    endo_idx[e, : len(ptx.endo_item_idx)] = ptx.endo_item_idx
                for s, (ser, ident) in enumerate(ptx.endorsements):
                    pi = pool_of.get(id(ident))
                    if pi is None:
                        pi = pool_of[id(ident)] = len(pool_rows)
                        pool_rows.append(default._match_row(plan, ser, ident))
                    idx_mat[e, s] = pi
            match = np.stack(pool_rows)[idx_mat]  # [E, S, P] gather
            groups.append((plan, match, endo_idx, tx_of))
            group_entries.append(ents)

        # static MVCC arrays (committed-version fill deferred to
        # validate time — it needs the predecessor's state commit)
        mvcc_txs = []
        has_range = False
        for ptx in txs:
            if ptx.rwset is None or not ptx.undetermined:
                mvcc_txs.append(
                    mvcc_ops.TxRWSet(reads=[], writes=[], range_reads=[])
                )
                continue
            if any(n.range_queries for n in ptx.rwset.ns.values()):
                has_range = True
            reads, writes, rqs = ptx.rwset.mvcc_form()
            mvcc_txs.append(
                mvcc_ops.TxRWSet(reads=reads, writes=writes, range_reads=rqs)
            )
        static = mvcc_ops.prepare_block_static(mvcc_txs, bucketed=True)
        return _DevicePre(
            groups=groups, group_entries=group_entries, static=static,
            has_range=has_range, policies=self.policies,
        )

    def _validate_device(self, block, txs, items, handle, dpre):
        """One-dispatch-one-readback validation (device_block): returns
        (filter, batch, history) or None to fall back."""
        import time

        from fabric_tpu.peer.device_block import DeviceBlockPipeline

        t0 = time.perf_counter()
        # committed-range phantom re-execution (host state reads)
        if dpre.has_range:
            for ptx in txs:
                if (
                    ptx.undetermined and not ptx.is_config
                    and ptx.rwset is not None
                    and self._committed_range_phantom(ptx)
                ):
                    ptx.code = C.PHANTOM_READ_CONFLICT

        T = len(txs)
        t_bucket = int(dpre.static.read_keys.shape[0])
        structural = np.zeros(t_bucket, bool)
        creator_idx = np.full(t_bucket, -1, np.int32)
        for ptx in txs:
            if ptx.undetermined and not ptx.is_config:
                structural[ptx.idx] = True
                creator_idx[ptx.idx] = ptx.creator_item_idx

        committed = self._committed_versions(dpre.static.read_key_set)
        mvcc_arrays = dpre.static.device_args(committed)
        t0 = self._t("state_fill", t0)

        if self._device_pipeline is None:
            self._device_pipeline = DeviceBlockPipeline()
        fetch2 = self._device_pipeline.run(
            handle, creator_idx, structural, dpre.groups, mvcc_arrays,
            t_bucket,
        )
        t0 = self._t("stage2_dispatch", t0)
        group_entries = dpre.group_entries
        out = fetch2()
        t0 = self._t("device_wait", t0)

        # consumption-unsafe rows → exact host interpreter path
        for safe_bits, ents in zip(out["safe"], group_entries):
            if not np.all(safe_bits[: len(ents)]):
                return None

        sig_valid = out["sig_valid"]
        for ptx in txs:
            if ptx.undetermined and ptx.creator_item_idx >= 0:
                if not (
                    ptx.creator_item_idx < len(sig_valid)
                    and sig_valid[ptx.creator_item_idx]
                ):
                    ptx.code = C.BAD_CREATOR_SIGNATURE
        for ptx in txs:
            if ptx.is_config and ptx.undetermined:
                ptx.code = self._validate_config(block, ptx)
        for ptx in txs:
            if not ptx.undetermined or ptx.is_config:
                continue
            if not out["policy_ok"][ptx.idx]:
                ptx.code = C.ENDORSEMENT_POLICY_FAILURE
        for ptx in txs:
            if not ptx.undetermined:
                continue
            if ptx.is_config or out["valid"][ptx.idx]:
                ptx.code = C.VALID
            else:
                ptx.code = (
                    C.PHANTOM_READ_CONFLICT
                    if out["phantom"][ptx.idx]
                    else C.MVCC_READ_CONFLICT
                )

        tx_filter = bytes(ptx.code for ptx in txs)
        batch, history = self._build_updates(block.header.number, txs)
        return tx_filter, batch, history

    def _mvcc_inputs(self, txs):
        mvcc_txs = []
        all_read_keys = set()
        for ptx in txs:
            if ptx.rwset is None or not ptx.undetermined:
                mvcc_txs.append(mvcc_ops.TxRWSet(reads=[], writes=[], range_reads=[]))
                continue
            # re-execute range queries against COMMITTED state: a key
            # committed after simulation but inside the range is a
            # phantom even with no in-block writer (the reference
            # merges committed state into the range re-check,
            # validation/validator.go:205-247, combined_iterator.go:44).
            # Per-result version staleness rides the normal read checks;
            # in-block writers ride the id-interval kernel check.
            if self._committed_range_phantom(ptx):
                ptx.code = C.PHANTOM_READ_CONFLICT
                mvcc_txs.append(mvcc_ops.TxRWSet(reads=[], writes=[], range_reads=[]))
                continue
            reads, writes, rqs = ptx.rwset.mvcc_form()
            mvcc_txs.append(
                mvcc_ops.TxRWSet(reads=reads, writes=writes, range_reads=rqs)
            )
            all_read_keys.update(k for k, _ in reads)
        return mvcc_txs, self._committed_versions(all_read_keys)

    def _committed_versions(self, all_read_keys) -> dict:
        """Bulk-load committed versions for a set of mvcc-form keys
        (the preLoadCommittedVersionOfRSet analog,
        validation/validator.go:27-78)."""
        committed: dict = {}
        if all_read_keys:
            pub_keys = [
                (k[1], k[2]) for k in all_read_keys if k[0] == "pub"
            ]
            vers = self.state.get_versions_bulk(pub_keys)
            for k in all_read_keys:
                if k[0] == "pub" and (k[1], k[2]) in vers:
                    committed[k] = vers[(k[1], k[2])]
                elif k[0] == "pvt":
                    v = self.state.get_version(f"{k[1]}${k[2]}#hashed", _hex(k[3]))
                    if v is not None:
                        committed[k] = v
        return committed

    def _committed_range_phantom(self, ptx) -> bool:
        """True iff some committed key falls inside a recorded range
        query but is missing from its recorded results (end_key == ''
        means unbounded, per the reference's open-ended iterators)."""
        for ns_name, n in ptx.rwset.ns.items():
            for start, end, results in n.range_queries:
                recorded = {k for k, _ in results}
                for key, _vv in self.state.get_state_range(ns_name, start, end):
                    if key not in recorded:
                        return True
        return False

    def _validate_config(self, block, ptx) -> int:
        """Config-tx validation: structure must parse as a
        ConfigEnvelope and the configured processor must accept it —
        CONFIG envelopes are never rubber-stamped
        (v20/validator.go:397-419)."""
        try:
            env = protoutil.unmarshal(common_pb2.Envelope, block.data.data[ptx.idx])
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            cfg_env = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
        except Exception:
            return C.BAD_PAYLOAD
        if block.header.number == 0:
            # genesis config is the channel's trust anchor — verified
            # out-of-band by the joining admin, not by prior state
            return C.VALID
        if self.config_processor is not None:
            try:
                return self.config_processor.validate_config_tx(ptx, cfg_env)
            except Exception:
                return C.INVALID_OTHER_REASON
        return C.VALID

    def _build_updates(self, block_num: int, txs):
        batch = UpdateBatch()
        history = []
        for ptx in txs:
            if ptx.code != C.VALID or ptx.rwset is None:
                continue
            ver = (block_num, ptx.idx)
            for ns_name in sorted(ptx.rwset.ns):
                n = ptx.rwset.ns[ns_name]
                for key in sorted(n.writes):
                    val = n.writes[key]
                    if val is None:
                        batch.delete(ns_name, key, ver)
                    else:
                        batch.put(ns_name, key, val, ver)
                    history.append((ns_name, key, ptx.idx))
                for coll in sorted(n.hashed):
                    hns = f"{ns_name}${coll}#hashed"
                    for kh, (vh, is_del) in sorted(n.hashed[coll].get("writes", {}).items()):
                        if is_del:
                            batch.delete(hns, _hex(kh), ver)
                        else:
                            batch.put(hns, _hex(kh), vh, ver)
        return batch, history


class DefaultValidation(ValidationPlugin):
    """Built-in plugin (analog builtin/default_validation.go +
    v20/validation_logic.go): evaluate one (tx, namespace) pair's
    chaincode policy over the tx's verified endorsements.  Plans are
    compiled once per policy object and cached (the reference caches
    per plugin^channel, plugin_validator.go)."""

    def __init__(self):
        # keyed by the (frozen, hashable) policy AST itself — id()-keys
        # could alias a recycled address after a config update GCs the
        # old policy object
        self._plan_cache: dict[object, pol.BatchPlan] = {}

    def _plan(self, policy) -> pol.BatchPlan:
        plan = self._plan_cache.get(policy)
        if plan is None:
            plan = pol.compile_plan(policy)
            self._plan_cache[policy] = plan
        return plan

    def _match_row(self, plan: pol.BatchPlan, serialized: bytes, ident):
        """Memoized principal-match row for one endorser identity —
        a block re-presents the same few certs thousands of times."""
        cache = getattr(plan, "_row_cache", None)
        if cache is None:
            cache = plan._row_cache = {}
        hit = cache.get(serialized)
        if hit is not None and hit[0] is ident:
            return hit[1]
        # pin the Identity object in the entry: a hit requires the SAME
        # object, so an MSP-cache invalidation (new Identity instances)
        # can never be served a stale principal-match row
        row = np.array([p.matched_by(ident) for p in plan.principals], bool)
        cache[serialized] = (ident, row)
        return row

    def validate_batch_group(self, ctx: BlockValidationCtx, group):
        """ONE vectorized policy reduction per distinct policy over all
        its (tx, namespace) entries — the per-tx closure walk of the
        reference (cauthdsl.go:39) becomes a [T, S, P] count reduction;
        the exact consumption interpreter only runs for the rare rows
        where a signature matches two distinct principals."""
        out = [False] * len(group)
        by_policy: dict[int, list] = {}
        policies: dict[int, object] = {}
        for idx, (ptx, ns) in enumerate(group):
            info = ctx.policy_provider.info(ns)
            key = id(info.policy)
            policies[key] = info.policy
            by_policy.setdefault(key, []).append((idx, ptx))
        for key, entries in by_policy.items():
            policy = policies[key]
            plan = self._plan(policy)
            P = len(plan.principals)
            T = len(entries)
            S = max((len(p.endorsements) for _, p in entries), default=0) or 1
            M = np.zeros((T, S, P), bool)
            for t, (_, ptx) in enumerate(entries):
                for s, (ser, ident) in enumerate(ptx.endorsements):
                    if ctx.sig_valid[ptx.endo_item_idx[s]]:
                        M[t, s] = self._match_row(plan, ser, ident)
            safe = plan.consumption_safe_batch(M)
            ok = plan.evaluate_counts_batch(M)
            for t, (idx, ptx) in enumerate(entries):
                if safe[t]:
                    out[idx] = bool(ok[t])
                else:
                    m = M[t, : len(ptx.endorsements)]
                    out[idx] = bool(pol.evaluate(policy, m))
        return out


def _sig_item(ident: Identity, message: bytes, der_sig: bytes):
    r, s = sig_to_ints(der_sig)
    qx, qy = ident.public_numbers
    return (int.from_bytes(hashlib.sha256(message).digest(), "big"), r, s, qx, qy)


def _hex(b: bytes) -> str:
    return b.hex()

"""The block validator: TPU-batched equivalent of the reference's
commit-path validation (the north-star component).

Reference shape (SURVEY §3.2): TxValidator v20 runs a goroutine per tx
(core/committer/txvalidator/v20/validator.go:180-265) doing envelope
checks + creator ECDSA verify, dup-txid, then the plugin dispatcher
walks each namespace's validation plugin which verifies every
endorsement signature inside the policy tree
(statebased/validator_keylevel.go:244-260, cauthdsl.go:24-110); the
ledger then runs a serial MVCC loop (validation/validator.go:81-118).

TPU-first re-ordering — compute first, control flow after:

  phase 0 (host)  parse every envelope, collect EVERY signature in the
                  block — creator sigs and endorsement sigs alike — as
                  (digest, r, s, qx, qy) tuples; bulk-load committed
                  versions for every read key.
  phase 1 (TPU)   ONE batched ECDSA verify over all signatures
                  (ops.p256), ONE vectorized policy reduction per
                  distinct policy shape (peer.device_block).
  phase 2 (TPU)   ONE MVCC kernel call over the whole block (ops.mvcc)
                  with pre_ok = structural ∧ creator-sig ∧ policy.
  phase 3 (host)  TRANSACTIONS_FILTER codes, update batch, history
                  writes for the ledger.

The plugin SPI (``ValidationPlugin``) keeps the reference's pluggable
boundary (core/handlers/validation/api/validation.go:26-38): the
built-in ``DefaultValidation`` implements phase-1 policy logic; custom
plugins get the same per-namespace dispatch
(plugindispatcher/dispatcher.go:102-221).
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from dataclasses import dataclass, field
from functools import cached_property

import numpy as np

from fabric_tpu import faults as _faults
from fabric_tpu import protoutil
from fabric_tpu.crypto import policy as pol
from fabric_tpu.crypto.identity import Identity, sig_to_ints
from fabric_tpu.ledger.rwset import TxRWSet
from fabric_tpu.ledger.statedb import UpdateBatch
from fabric_tpu.ops import mvcc as mvcc_ops
from fabric_tpu.ops import p256
from fabric_tpu.protos import common_pb2, configtx_pb2, transaction_pb2

C = transaction_pb2.TxValidationCode

_log = logging.getLogger("fabric_tpu.validator")


class ValidationPlugin:
    """SPI mirroring validation.Plugin (api/validation.go:26-38), but
    batch-shaped: given per-tx endorsement validity bits + identities,
    decide policy satisfaction for every tx at once."""

    def validate_batch(self, ctx: "BlockValidationCtx") -> np.ndarray:
        """→ [T] bool policy-ok for txs this plugin owns."""
        raise NotImplementedError


@dataclass
class NamespaceInfo:
    """Validation info for one namespace (the dispatcher's
    GetInfoForValidate analog, plugindispatcher/dispatcher.go:244-263)."""

    policy: object  # crypto.policy AST
    plugin: str = "default"
    # {coll: {"member_orgs": [...], "required_peer_count": int,
    #  "max_peer_count": int, "btl": int}} — static assemblies;
    # lifecycle-backed providers read the committed definition instead
    collections: dict = field(default_factory=dict)


class PolicyProvider:
    """namespace → NamespaceInfo; backed by the lifecycle cache once
    chaincode lifecycle lands (reference: _lifecycle state)."""

    def __init__(self, infos: dict[str, NamespaceInfo], default: NamespaceInfo | None = None):
        self.infos = dict(infos)
        self.default = default

    def info(self, namespace: str) -> NamespaceInfo | None:
        return self.infos.get(namespace) or self.default

    def collection(self, namespace: str, coll: str) -> dict | None:
        """Collection config for (namespace, coll), or None when
        undefined — undefined collections are treated as
        maximally-private (own org only) by the dissemination layer."""
        info = self.info(namespace)
        if info is None:
            return None
        return getattr(info, "collections", {}).get(coll)


@dataclass(slots=True)
class ParsedTx:
    idx: int
    code: int = C.NOT_VALIDATED
    txid: str = ""
    channel: str = ""
    creator: bytes = b""
    namespaces: tuple = ()
    endorsements: list = field(default_factory=list)  # (endorser_serialized, item)
    creator_item_idx: int = -1
    endo_item_idx: list = field(default_factory=list)
    is_config: bool = False
    rwset_bytes: bytes | None = None  # lazy wire form (native fast path)
    _rwset: object = None
    # creator verified HOST-side (idemix/anonymous creators carry no EC
    # key for the batch lane): creator_item_idx stays -1 and the device
    # path maps the tx to the always-true sentinel signature lane
    host_creator_ok: bool = False

    @property
    def undetermined(self) -> bool:
        return self.code == C.NOT_VALIDATED

    @property
    def rwset(self):
        """Parsed rwset; LAZY when the native fast path supplied flat
        arrays instead (only the rare host-fallback paths ever touch
        this).  A parse failure here is unreachable for txs the native
        parser validated, but fails closed (BAD_RWSET) regardless."""
        if self._rwset is None and self.rwset_bytes is not None:
            try:
                self._rwset = TxRWSet.from_bytes(self.rwset_bytes)
            except Exception:
                self.code = C.BAD_RWSET
                self._rwset = TxRWSet()
        return self._rwset

    @rwset.setter
    def rwset(self, value):
        self._rwset = value


@dataclass
class BlockValidationCtx:
    txs: list
    sig_valid: np.ndarray  # [n_items] bool, global signature batch
    msp_manager: object
    policy_provider: PolicyProvider


@dataclass
class PendingBlock:
    """A launched-but-not-synced block: the handle between
    validate_launch and validate_finish.  ``txids`` feeds the NEXT
    block's extra_txids; the triple is produced by validate_finish."""

    block: object
    txs: list
    items: object
    fetch: object          # p256 VerifyHandle
    dpre: object           # _DevicePre or None
    overlay: object = None  # predecessor UpdateBatch (in-flight commit)
    fetch2: object = None   # stage-2 packed fetch, set by _launch_device
    range_phantom: frozenset = frozenset()  # tx idxs failing range re-exec
    fb: object = None       # _FastBlock of a columnar parse, or None
    hd_bytes: bytes = None  # pre-serialized header+data (ledger commit)

    @cached_property
    def txids(self) -> set:
        # hot-path consumers (dup checks, pipeline overlay handoff)
        # hit this repeatedly — the txid set is immutable after parse
        return {ptx.txid for ptx in self.txs if ptx.txid}


@dataclass
class _FastBlock:
    """Array-form block state for the fully vectorized (columnar)
    parse: everything the device-path stages need, with NO per-tx
    Python objects on the hot path.  ParsedTx objects still exist for
    the slow lanes and post-commit consumers, but their endorsement
    lists / namespaces are only materialized on demand
    (_materialize_for_host)."""

    native: object            # blockparse.ParsedBlock
    codes: object             # [n] int32 LIVE codes (synced with ptx)
    is_config: object         # [n] bool
    c_ok: object              # [n] bool: eligible columnar endorser txs
    creator_item: object      # [n] int64 global sig-item idx; -1 none
    uid_mat: object           # [n, S] int64 pool row (uid+1); 0 = pad
    endo_idx_mat: object      # [n, S] int32 global item idx; -1 = pad
    ecnt: object              # [n] included endorsement count
    idents: list              # uid → Identity | None
    sers: list                # uid → serialized identity bytes
    has_ec: object            # [n_ids+1] bool
    fallback_idx: list        # envelope indices parsed on the py path
    materialized: bool = False


class _SlowItems:
    """add_slow shim for fallback envelopes inside the columnar parse:
    collects legacy tuples; positions are LOCAL and get rebased past
    the fast block once its size is known."""

    __slots__ = ("slow",)

    def __init__(self):
        self.slow = []

    def add_slow(self, item) -> int:
        self.slow.append(item)
        return len(self.slow) - 1


class _HostVerifyHandle:
    """A completed CPU verify masquerading as a fetch handle: the
    degraded device lane routes blocks here (``ops/p256.verify_host``
    under ``faults.shield()``, pure-Python ``ec_ref`` as the last
    ditch).  It deliberately exposes NO ``device_out`` — the fused
    stage-2 program never launches for these blocks, so they take the
    host MVCC path with identical verdicts."""

    __slots__ = ("result",)

    def __init__(self, result: list):
        self.result = result

    def fetch(self) -> list:
        return self.result

    def __call__(self) -> list:
        return self.result


class _GuardedHandle:
    """A device VerifyHandle wrapped with the lane guard's success /
    failure / deadline accounting at the fetch (device sync) boundary.
    ``device_out`` forwards so the fused stage-2 path is unchanged; a
    fetch-side device failure re-verifies THIS block on the CPU
    (correctness first) and counts toward the degraded latch."""

    __slots__ = ("_h", "_guard", "_validator", "_items", "_result")

    def __init__(self, handle, guard, validator, items):
        self._h = handle
        self._guard = guard
        self._validator = validator
        self._items = items
        self._result = None

    @property
    def device_out(self):
        return getattr(self._h, "device_out", None)

    @property
    def n_real(self) -> int:
        return getattr(self._h, "n_real", 0)

    def fetch(self) -> list:
        if self._result is not None:
            return self._result
        t0 = time.perf_counter()
        try:
            out = self._h()
        except Exception as e:
            self._guard.record_failure(e)
            self._guard.count_fallback()  # this block rides the CPU
            _log.warning(
                "device verify sync failed (%s) — re-verifying this "
                "block on the CPU fallback", e,
            )
            self._result = self._validator._host_verify_fallback(
                self._items
            )
            return self._result
        if not self._guard.check_deadline(time.perf_counter() - t0):
            self._guard.record_success()
        self._result = out
        return out

    def __call__(self) -> list:
        return self.fetch()


@dataclass
class _DevicePre:
    """State-independent device-path inputs built at preprocess time
    (prefetch thread): policy groups + static MVCC arrays.  `policies`
    pins the provider the plans were compiled against — validate()
    re-preprocesses if the channel config rotated in between."""

    groups: list          # [(plan, match [E,S,P], endo_idx [E,S], tx_of [E])]
    group_entries: list   # parallel: [(ptx, info), ...] per group
    static: object        # mvcc_ops.StaticBlock
    has_range: bool
    policies: object
    rwp: object = None    # native mvcc_prep flat arrays (fast blocks)
    ns_names: list = None
    ukeys: list = None    # decoded unique key strings (shared w/ fill)
    # True iff fb.codes tracks every later per-tx code assignment (the
    # columnar builder + the launch-time dup check keep it live) — the
    # gate for the vectorized state_fill in _launch_device
    codes_synced: bool = False


class BlockValidator:
    """Validate(block) → (tx_filter, UpdateBatch, history_writes)."""

    def __init__(
        self,
        msp_manager,
        policy_provider: PolicyProvider,
        state_db,
        block_store=None,
        plugins: dict[str, ValidationPlugin] | None = None,
        config_processor=None,
        verify_chunk: int = 0,
        mesh_devices: int = 0,
        host_stage_workers: int = 0,
        recode_device: bool = False,
        host_stage_mode: str = "thread",
        device_fail_threshold: int = 0,
        device_retries: int = 2,
        device_recovery_s: float = 30.0,
        verify_deadline_ms: float = 0.0,
        state_resident: bool = False,
        state_resident_mb: int = 64,
        state_resident_range_bits: int = 12,
        channel: str = "",
        mesh_topology=None,
    ):
        self.msp = msp_manager
        self.policies = policy_provider
        self.state = state_db
        self.blocks = block_store
        self.plugins = {"default": DefaultValidation(), **(plugins or {})}
        self.config_processor = config_processor
        self._device_pipeline = None
        # signature-batch microbatching: split each block's verify
        # batch into chunks of this many signatures with
        # double-buffered async dispatch (ops.p256v3), so chunk k's
        # device compute overlaps chunk k+1's host staging.  0 = one
        # monolithic launch (nodeconfig ``verify_chunk``).
        self.verify_chunk = int(verify_chunk)
        # latched by set_verify_chunk / set_host_stage_workers (the
        # autopilot actuators), applied at the next block boundary.
        # The latch is locked: the controller thread sets while the
        # prefetch thread applies, and a bare read-then-clear would
        # drop a step landing between the read and the None store.
        self._knob_lock = threading.Lock()
        self._pending_verify_chunk: int | None = None
        # device-mesh sharding of the production dispatch (nodeconfig
        # ``mesh_devices`` + the pod-scale topology knobs): batch
        # lanes of the verify kernel AND the fused stage-2 program
        # shard under the declarative partition rules
        # (fabric_tpu/parallel/mesh.py) over the resolved mesh —
        # mesh_devices 0 = off, -1 = all local, n = first n (the
        # 1-process special case); a ``mesh_topology``
        # (parallel.topology.MeshTopology) layers ``mesh_shape`` grids
        # and jax.distributed process-spanning fabrics on top.
        # Bit-equal to single-device (tests/test_multidevice.py,
        # tests/test_partition_rules.py); a 1-wide data axis degrades
        # to None so CPU-only hosts pay nothing.
        self.mesh_devices = int(mesh_devices)
        if mesh_topology is not None and mesh_topology.configured:
            self.mesh = mesh_topology.resolve()
        elif self.mesh_devices:
            from fabric_tpu.parallel.mesh import resolve_mesh

            self.mesh = resolve_mesh(self.mesh_devices)
        else:
            self.mesh = None
        # host staging pool (nodeconfig ``host_stage_workers``): the
        # per-block HOST pipeline — envelope parse fan-out in
        # preprocess_many, the per-signature admission + batch
        # inversion + residue dgemm in prepare_cols (sharded along the
        # lane axis at bucket boundaries), and device-path
        # preprocessing overlapping the next block's parse — shards
        # over a persistent worker pool.  0 = off (serial staging,
        # CPU-only hosts pay nothing), -1 = one worker per core.
        # Bit-equal to serial staging (every staged lane is
        # lane-independent; pinned the way sharded ≡ single-device is).
        self.host_stage_workers = int(host_stage_workers)
        if self.host_stage_workers:
            from fabric_tpu.parallel.hostpool import resolve_host_pool

            if host_stage_mode == "process":
                # the validator's staging is SHARED-MEMORY by design:
                # workers write row slabs into preallocated arrays in
                # place and the fan-out submits bound methods/closures
                # — neither crosses a process boundary.  Process mode
                # is for custom picklable staging workloads on a
                # directly-constructed HostStagePool; here it would
                # crash the first validated block, so coerce loudly.
                _log.warning(
                    "host_stage_mode='process' is not usable for the "
                    "validator's in-place staging; using threads (the "
                    "staging hot loops release the GIL)"
                )
                host_stage_mode = "thread"
            self.host_pool = resolve_host_pool(
                self.host_stage_workers, mode=host_stage_mode
            )
        else:
            self.host_pool = None
        # window recoding location (nodeconfig ``recode_device``):
        # ship u1/u2 as 16-bit limbs and derive the 4-bit window digits
        # in the stage-1 kernel — the packed H2D frame shrinks (window
        # planes 4×), so pooled shards and mesh shards upload less per
        # worker/chip.  Default False (host recode — the C ec_prepare
        # path computes windows for free, and CPU-only hosts see no
        # H2D bottleneck to shrink).  Bit-equal either way.
        self.recode_device = bool(recode_device)
        # device-lane degradation guard (peer/degrade.py, nodeconfig
        # device_fail_threshold / device_retries / device_recovery_s /
        # verify_deadline_ms): bounded-retry device launches that latch
        # a degraded CPU mode (ops/p256.verify_host + the host MVCC
        # path — correctness identical, the channel stays live) after
        # consecutive failures, with a periodic recovery probe.
        # threshold 0 = guard off entirely (today's raise-through
        # behavior; tier-1 and CPU-only hosts unchanged).
        self.channel = channel
        if device_fail_threshold > 0:
            from fabric_tpu.peer.degrade import DeviceLaneGuard

            self.device_guard = DeviceLaneGuard(
                retries=device_retries,
                fail_threshold=device_fail_threshold,
                recovery_s=device_recovery_s,
                deadline_ms=verify_deadline_ms,
                channel=channel,
            )
        else:
            self.device_guard = None
        # device-resident MVCC state (fabric_tpu/state, nodeconfig
        # ``state_resident`` / ``state_resident_mb`` /
        # ``state_resident_range_bits``): an LRU key-range residency
        # cache keeps committed versions in DEVICE memory across
        # blocks — the fused stage-2 program reads them there and the
        # per-block host state_fill shrinks to the miss/overlay set,
        # with each committed write-set applied as a delta scatter at
        # the commit boundary (CommitPipeline → resident_commit).
        # Default OFF: the host state_fill path — which also stays as
        # the bit-equal fallback oracle for misses, range queries,
        # eviction pressure and device failures — is the exact
        # existing path.
        if state_resident:
            from fabric_tpu.state import resolve_residency

            self.resident = resolve_residency(
                True, state_resident_mb, state_resident_range_bits,
                mesh=self.mesh, channel=channel,
            )
        else:
            self.resident = None
        # optional phase accumulator (seconds per phase, summed across
        # blocks) — the bench publishes it as the per-phase breakdown
        # artifact; None = no instrumentation overhead
        self.timings: dict | None = None
        # the same stages feed production telemetry unconditionally,
        # so a live peer's /metrics and BENCH_breakdown.json agree
        from fabric_tpu.ops_metrics import global_registry

        self._stage_hist = global_registry().histogram(
            "validator_stage_seconds",
            "per-block validator stage time (s), bench-breakdown stages",
        )
        # the span tracer mirrors the same stages onto the per-block
        # timeline: _t records each stage under whatever span the
        # calling thread is attached to (the pipeline's prefetch/
        # launch/finish spans), so no span handles thread through here
        from fabric_tpu.observe import global_tracer

        self._tracer = global_tracer()

    def close(self) -> None:
        """Release validator-owned resources — the host staging pool's
        worker threads outlive GC pins (bench result lists, channel
        registries), so teardown paths must call this (PeerChannel.stop
        does).  Idempotent."""
        pool, self.host_pool = self.host_pool, None
        if pool is not None:
            pool.shutdown()

    # -- runtime re-knobbing (autopilot actuator) --------------------------

    def set_verify_chunk(self, n: int) -> None:
        """Request a new signature-verify chunk size, applied at the
        next block boundary (the top of ``preprocess`` /
        ``preprocess_many``, where this block's verify dispatch has
        not started) — a block's chunked launch always runs under one
        chunk size, never a mid-window mix.  0 = monolithic."""
        with self._knob_lock:
            self._pending_verify_chunk = max(0, int(n))

    def set_host_stage_workers(self, n: int) -> None:
        """Request a new host staging pool size (the autopilot's
        ``host_stage_workers`` actuator), applied at the next block
        boundary: ``n >= 2`` resizes the live pool (HostStagePool.
        set_workers — drain-and-rebuild at a task boundary) or builds
        one where none existed; ``n < 2`` closes the pool back to
        serial staging.  Bit-equal either way — pooled ≡ serial is
        pinned, so the knob only moves time."""
        with self._knob_lock:
            self._pending_host_workers = max(0, int(n))

    def _apply_pending_knobs(self) -> None:
        with self._knob_lock:
            n, self._pending_verify_chunk = (
                self._pending_verify_chunk, None,
            )
            w = getattr(self, "_pending_host_workers", None)
            self._pending_host_workers = None
        if n is not None:
            self.verify_chunk = n
        if w is not None:
            if w < 2:
                pool, self.host_pool = self.host_pool, None
                self.host_stage_workers = 0
                if pool is not None:
                    pool.shutdown()
            elif self.host_pool is not None:
                from fabric_tpu.parallel.hostpool import clamp_workers

                self.host_pool.set_workers(w)
                # report the clamped TARGET (what the pool will be
                # after its idle-boundary swap) — pool.workers still
                # reads the pre-swap count here, and nothing would
                # ever write the attribute back after the swap
                self.host_stage_workers = clamp_workers(w)
            else:
                from fabric_tpu.parallel.hostpool import resolve_host_pool

                self.host_pool = resolve_host_pool(w)
                self.host_stage_workers = (
                    self.host_pool.workers
                    if self.host_pool is not None else 0
                )

    def _t(self, key: str, t0: float) -> float:
        t1 = time.perf_counter()
        if self.timings is not None:
            self.timings[key] = self.timings.get(key, 0.0) + (t1 - t0)
        self._stage_hist.observe(t1 - t0, stage=key)
        self._tracer.add(key, t0, t1)  # no-op off the traced paths
        return t1

    def warmup(self, n_sigs: int = 16) -> None:
        """Compile (or load from the persistent cache) the signature
        kernel for the smallest batch bucket before serving traffic —
        first-block latency must not eat a cold compile."""
        from fabric_tpu.crypto import ec_ref

        k = ec_ref.SigningKey.generate()
        e = ec_ref.digest_int(b"warmup")
        r, s = k.sign_digest(e)
        p256.verify_host([(e, r, s, *k.public)] * n_sigs)

    # -- device lane: guarded dispatch + CPU fallback ----------------------

    def _verify_launch_guarded(self, items):
        """One block's verify dispatch through the device-lane guard
        (bounded retry → degraded CPU fallback); the raw launch when no
        guard is configured — the zero-overhead default."""

        def launch():
            return p256.verify_launch(
                items, chunk=self.verify_chunk or None, mesh=self.mesh,
                pool=self.host_pool, recode_device=self.recode_device,
            )

        if self.device_guard is None:
            return launch()
        out = self.device_guard.run_launch(
            launch, lambda: self._host_verify_handle(items)
        )
        if isinstance(out, _HostVerifyHandle):
            return out
        return _GuardedHandle(out, self.device_guard, self, items)

    def _verify_launch_many_guarded(self, itemsets, pool=None):
        """Coalesced multi-block dispatch through the guard: one
        device attempt covers the group; a degraded lane verifies each
        block's batch on the CPU instead (every block counted on
        ``fallback_blocks_total``)."""

        def launch():
            return p256.verify_launch_many(
                itemsets, chunk=self.verify_chunk or None,
                mesh=self.mesh, pool=pool,
                recode_device=self.recode_device,
            )

        if self.device_guard is None:
            return launch()
        out = self.device_guard.run_launch(
            launch,
            lambda: [self._host_verify_handle(it) for it in itemsets],
            fallback_count=len(itemsets),
        )
        return [
            h if isinstance(h, _HostVerifyHandle)
            else _GuardedHandle(h, self.device_guard, self, it)
            for h, it in zip(out, itemsets)
        ]

    def _host_verify_handle(self, items) -> "_HostVerifyHandle":
        """The degraded route for one block's signature batch: a
        synchronous CPU verify with no async device handle, no fused
        stage-2, no mesh/chunk/pool machinery."""
        return _HostVerifyHandle(self._host_verify_fallback(items))

    def _host_verify_fallback(self, items) -> list:
        """items → list[bool] on the CPU lane.  ``ops/p256.verify_host``
        under ``faults.shield()`` first (the plain synchronous path);
        if even that lane is dead, the pure-Python ``ec_ref`` oracle
        verifies signature by signature — slow, dependency-free, and
        bit-identical in accept set (low-S included)."""
        tuples = items.tuples() if hasattr(items, "tuples") else list(items)
        if not tuples:
            return []
        try:
            with _faults.shield():
                return [bool(v) for v in p256.verify_host(tuples)]
        except Exception as e:
            _log.warning(
                "CPU verify_host lane failed too (%s) — falling back to "
                "the pure-Python reference verifier for %d signatures",
                e, len(tuples),
            )
            from fabric_tpu.crypto import ec_ref

            return [
                ec_ref.verify_digest((qx, qy), e_, r, s)
                for (e_, r, s, qx, qy) in tuples
            ]

    # -- phase 0: parse + collect -----------------------------------------

    def _parse(self, block: common_pb2.Block) -> tuple[list, list]:
        """Parse every envelope + collect the signature batch.

        Fast path: the native C++ pre-parser (fabric_tpu.native) walks
        the whole block's wire format, hashes every message and splits
        every DER signature in ONE call; envelopes it cannot fully
        handle (config txs, malformed bytes) fall back to the Python
        path below, envelope by envelope — identical verdicts either
        way (tests/test_native_parse.py pins the equivalence)."""
        from fabric_tpu.ops.p256v3 import SigCollector

        txs: list[ParsedTx] = []
        items = SigCollector()  # column-form signature batch
        seen_txids: dict[str, int] = {}
        native = None
        # config/genesis envelopes come back ok=0 from the native walk
        # and take the Python path per envelope — no number gate needed
        if len(block.data.data) >= 16:
            try:
                from fabric_tpu.native import blockparse as nbp

                native = nbp.parse_envelopes(list(block.data.data))
            except Exception:
                native = None
        if native is not None:
            out = self._parse_columnar(block, native)
            if out is not None:
                return out
        fast_ctx = self._fast_ctx(native) if native is not None else None
        for i, env_bytes in enumerate(block.data.data):
            if fast_ctx is not None and fast_ctx["ok"][i]:
                if self._parse_fast(i, fast_ctx, txs, items, seen_txids):
                    continue
                # fast path bowed out (e.g. an idemix creator whose
                # proof is not a DER signature): python path below
            self._parse_one_py(i, env_bytes, block, txs, items, seen_txids)

        # rwsets of native-fast endorser txs: ONE C call parses, interns
        # keys, and emits flat arrays; txs it cannot cover (ranges,
        # hashed collections, malformed, non-UTF8) take the Python
        # parser tx by tx
        rwp = None
        if native is not None:
            use = np.zeros(len(txs), bool)
            for ptx in txs:
                if (
                    native.ok[ptx.idx] and ptx.undetermined
                    and not ptx.is_config
                ):
                    use[ptx.idx] = True
            if use.any():
                try:
                    from fabric_tpu.native import mvccprep_py

                    rwp = mvccprep_py.prep(native, use)
                except Exception:
                    rwp = None
                ns_names = rwp.ns_names() if rwp is not None else None
                for ptx in txs:
                    i = ptx.idx
                    if not use[i]:
                        continue
                    if rwp is not None and rwp.status[i] == 0:
                        s = int(rwp.tx_ns_start[i])
                        c = int(rwp.tx_ns_count[i])
                        ptx.namespaces = tuple(sorted(
                            ns_names[j] for j in rwp.ns_ids_flat[s:s + c]
                        ))
                        ptx.rwset_bytes = (
                            native.span(native.results_span, i) or b""
                        )
                    else:
                        self._py_rwset(ptx, native)
        return txs, items, rwp, None

    def _parse_columnar(self, block, native):
        """Fully vectorized parse of a native-pre-parsed block: the
        per-tx Python loop of ``_parse`` becomes numpy over the C++
        parser's arrays — txid binding is a [k,64] hex compare, in-block
        dup detection a row-unique, the signature batch a set of column
        gathers, and the policy-group inputs scatter into [n,S]
        matrices.  Identities resolve ONCE per distinct cert.

        Envelopes the columnar lane cannot carry (config txs, idemix
        creators, malformed bytes) run through ``_parse_one_py`` in
        block order, sharing the dup registry.  Returns None when no
        envelope qualifies (the legacy loop takes over)."""
        from fabric_tpu.ops.p256v3 import ColumnarSigBatch
        from fabric_tpu.utils.batching import next_pow2

        n = len(block.data.data)
        blob = native.blob
        n_ids = native.n_ids
        NOTV = int(C.NOT_VALIDATED)

        # -- interned identity resolution (once per distinct cert) ----
        idents: list = [None] * n_ids
        sers: list = [None] * n_ids
        known = np.zeros(n_ids + 1, bool)
        ivalid = np.zeros(n_ids + 1, bool)
        has_ec = np.zeros(n_ids + 1, bool)
        idemix_like = np.zeros(n_ids + 1, bool)
        span = native.ident_span
        for u in range(n_ids):
            o, ln = int(span[u, 0]), int(span[u, 1])
            ser = blob[o:o + ln]
            sers[u] = ser
            try:
                ident = self.msp.deserialize_identity(ser)
            except Exception as e:
                _log.debug("undeserializable identity in block: %s", e)
                continue
            idents[u] = ident
            known[u] = True
            ivalid[u] = bool(ident.is_valid)
            try:
                ident.public_numbers
                ident.rns_pub
                has_ec[u] = True
            except Exception:
                idemix_like[u] = ivalid[u] and not hasattr(ident, "cert")

        ok = native.ok.astype(bool)
        cu = native.creator_uid.astype(np.int64)
        cu_valid = cu >= 0
        cuc = np.where(cu_valid, cu, n_ids)
        fallback = ~ok | (cu_valid & idemix_like[cuc])
        columnar = ~fallback
        if not columnar.any():
            return None

        # -- txid binding: tx_id must equal hex(sha256(nonce‖creator))
        t_off = native.txid_span[:, 0]
        t_len = native.txid_span[:, 1]
        blob_u8 = np.frombuffer(blob, np.uint8)
        cand = columnar & (t_off >= 0) & (t_len == 64)
        bind_ok = np.zeros(n, bool)
        crows = np.flatnonzero(cand)
        if len(crows):
            txh = blob_u8[t_off[crows][:, None] + np.arange(64)[None, :]]
            dg = native.txid_digest[crows]
            hi, lo = dg >> 4, dg & 15
            hx = np.empty((len(crows), 64), np.uint8)
            hx[:, 0::2] = np.where(hi < 10, hi + 48, hi + 87)
            hx[:, 1::2] = np.where(lo < 10, lo + 48, lo + 87)
            bind_ok[crows] = (txh == hx).all(axis=1)

        # decoded txid strings (ledger index + dup-vs-ledger checks)
        txid_strs = [""] * n
        off_l, len_l = t_off.tolist(), t_len.tolist()
        for i in np.flatnonzero(columnar & (t_off >= 0)).tolist():
            txid_strs[i] = blob[off_l[i]:off_l[i] + len_l[i]].decode(
                "utf-8", "replace"
            )

        # -- duplicate txids + fallback envelopes (block order) -------
        codes = np.full(n, NOTV, np.int32)
        dup = np.zeros(n, bool)
        fb_txs: dict[int, ParsedTx] = {}
        shim = _SlowItems()
        fallback_idx = np.flatnonzero(fallback).tolist()
        if not fallback_idx:
            brows = np.flatnonzero(bind_ok)
            if len(brows) > 1:
                keys = blob_u8[t_off[brows][:, None] + np.arange(64)[None, :]]
                _, first = np.unique(keys, axis=0, return_index=True)
                d = np.ones(len(brows), bool)
                d[first] = False
                dup[brows] = d
        else:
            # mixed block: interleave fallback parsing with columnar
            # txid claims in envelope order so dup semantics match the
            # serial path exactly
            seen: dict[str, int] = {}
            fall_l = fallback.tolist()
            bind_l = bind_ok.tolist()
            data = block.data.data
            for i in range(n):
                if fall_l[i]:
                    sub: list = []
                    self._parse_one_py(i, data[i], block, sub, shim, seen)
                    fb_txs[i] = sub[0]
                elif bind_l[i]:
                    t = txid_strs[i]
                    if t in seen:
                        dup[i] = True
                    else:
                        seen[t] = i

        codes[columnar & ~bind_ok] = int(C.BAD_PROPOSAL_TXID)
        codes[dup] = int(C.DUPLICATE_TXID)
        live = columnar & bind_ok & ~dup
        csig = native.creator_sig_ok.astype(bool)
        c_ok = live & cu_valid & known[cuc] & ivalid[cuc] & has_ec[cuc] & csig
        codes[live & ~c_ok] = int(C.BAD_CREATOR_SIGNATURE)

        # -- signature batch: column gathers, zero per-item Python ----
        m = int(native.endo_count[:n].sum())
        tx_of_e = np.repeat(np.arange(n), native.endo_count[:n])
        e_ok_m = native.e_ok[:m].astype(bool) & (native.e_dup[:m] == 0)
        eu = native.e_uid[:m].astype(np.int64)
        eu_valid = eu >= 0
        euc = np.where(eu_valid, eu, n_ids)
        mask_e = c_ok[tx_of_e] & e_ok_m & eu_valid & known[euc] & has_ec[euc]

        c_rows = np.flatnonzero(c_ok)
        nc = len(c_rows)
        creator_item = np.full(n, -1, np.int64)
        creator_item[c_rows] = np.arange(nc)
        e_rows = np.flatnonzero(mask_e)
        ne = len(e_rows)
        e_item = np.full(m, -1, np.int64)
        e_item[e_rows] = nc + np.arange(ne)

        from fabric_tpu.ops import rns

        qx_pool = np.zeros((n_ids + 1, 2 * rns.N_CH), np.int32)
        qy_pool = np.zeros((n_ids + 1, 2 * rns.N_CH), np.int32)
        for u in range(n_ids):
            if has_ec[u]:
                a, b = idents[u].rns_pub
                qx_pool[u], qy_pool[u] = a, b

        digest_b = np.concatenate(
            [native.payload_digest[c_rows], native.e_digest[:m][e_rows]]
        )
        r_b = np.concatenate(
            [native.creator_r[c_rows], native.e_r[:m][e_rows]]
        )
        s_b = np.concatenate(
            [native.creator_s[c_rows], native.e_s[:m][e_rows]]
        )
        uid_items = np.concatenate([cu[c_rows], eu[e_rows]])
        items = ColumnarSigBatch(
            digest_b, r_b, s_b, qx_pool[uid_items], qy_pool[uid_items],
            np.ones(nc + ne, bool), ident_of=uid_items, idents=idents,
        )

        # -- per-tx endorsement matrices (policy-group inputs) --------
        inc = mask_e.astype(np.int64)
        csum = np.cumsum(inc) if m else np.zeros(0, np.int64)
        csum0 = np.concatenate([[0], csum])
        start = native.endo_start[:n].astype(np.int64)
        ecnt = (np.bincount(tx_of_e[e_rows], minlength=n)
                if ne else np.zeros(n, np.int64))
        S = max(4, next_pow2(int(ecnt.max()) if ne else 1))
        uid_mat = np.zeros((n, S), np.int64)
        endo_idx_mat = np.full((n, S), -1, np.int32)
        if ne:
            ordinal = (csum - 1) - csum0[start][tx_of_e]
            rr = tx_of_e[e_rows]
            cc = ordinal[e_rows]
            uid_mat[rr, cc] = eu[e_rows] + 1
            endo_idx_mat[rr, cc] = e_item[e_rows]

        # -- rwsets: one C call over the eligible txs -----------------
        rwp = None
        if c_ok.any():
            try:
                from fabric_tpu.native import mvccprep_py

                rwp = mvccprep_py.prep(native, c_ok)
            except Exception:
                rwp = None

        # -- ParsedTx shells (slow-lane fields left lazy) -------------
        code_l = codes.tolist()
        txs = [
            fb_txs[i] if i in fb_txs else
            ParsedTx(idx=i, code=code_l[i], txid=txid_strs[i])
            for i in range(n)
        ]
        ci_l = creator_item.tolist()
        cu_l = cu.tolist()
        if rwp is not None:
            st = rwp.status
            res_off = native.results_span[:, 0].tolist()
            res_len = native.results_span[:, 1].tolist()
            for i in c_rows.tolist():
                ptx = txs[i]
                ptx.creator = sers[cu_l[i]]
                ptx.creator_item_idx = ci_l[i]
                if st[i] == 0:
                    o = res_off[i]
                    ptx.rwset_bytes = blob[o:o + res_len[i]] if o >= 0 else b""
                else:
                    self._py_rwset(ptx, native)
        else:
            for i in c_rows.tolist():
                ptx = txs[i]
                ptx.creator = sers[cu_l[i]]
                ptx.creator_item_idx = ci_l[i]
                self._py_rwset(ptx, native)

        # fallback ptxs: rebase their slow item indices past the fast
        # block, then sync their codes into the live array
        if fallback_idx:
            base = items.n_fast
            items.slow = shim.slow
            is_cfg = np.zeros(n, bool)
            for i, ptx in fb_txs.items():
                if ptx.creator_item_idx >= 0:
                    ptx.creator_item_idx += base
                if ptx.endo_item_idx:
                    ptx.endo_item_idx = [k + base for k in ptx.endo_item_idx]
                codes[i] = int(ptx.code)
                is_cfg[i] = ptx.is_config
        else:
            is_cfg = np.zeros(n, bool)

        fb = _FastBlock(
            native=native, codes=codes, is_config=is_cfg, c_ok=c_ok,
            creator_item=creator_item, uid_mat=uid_mat,
            endo_idx_mat=endo_idx_mat, ecnt=ecnt, idents=idents,
            sers=sers, has_ec=has_ec, fallback_idx=fallback_idx,
        )
        return txs, items, rwp, fb

    def _materialize_for_host(self, txs, fb) -> None:
        """Fill the per-tx endorsement lists / namespaces the columnar
        parse left lazy — required before any host-dispatch validation
        path touches ParsedTx objects of a columnar block."""
        if fb is None or fb.materialized:
            return
        uid_mat, em = fb.uid_mat, fb.endo_idx_mat
        for i in np.flatnonzero(fb.c_ok).tolist():
            ptx = txs[i]
            k = int(fb.ecnt[i])
            if k and not ptx.endorsements:
                ptx.endo_item_idx = em[i, :k].tolist()
                ptx.endorsements = [
                    (fb.sers[int(uid_mat[i, s]) - 1],
                     fb.idents[int(uid_mat[i, s]) - 1])
                    for s in range(k)
                ]
            if not ptx.namespaces and ptx.rwset is not None:
                ptx.namespaces = tuple(sorted(ptx.rwset.ns))
        fb.materialized = True

    def _parse_one_py(self, i, env_bytes, block, txs, items, seen_txids):
        """Parse ONE envelope on the Python path (config txs, idemix
        creators, malformed bytes, non-native blocks) — appends a
        ParsedTx and its signature items.  Shared by the legacy loop
        and the columnar fast path's fallback lane; ``seen_txids`` is
        the block-order dup registry both lanes feed."""
        ptx = ParsedTx(idx=i)
        txs.append(ptx)
        if not env_bytes:
            ptx.code = C.NIL_ENVELOPE
            return
        try:
            env = protoutil.unmarshal(common_pb2.Envelope, env_bytes)
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            ch = protoutil.unmarshal(
                common_pb2.ChannelHeader, payload.header.channel_header
            )
            sh = protoutil.unmarshal(
                common_pb2.SignatureHeader, payload.header.signature_header
            )
        except Exception:
            ptx.code = C.BAD_PAYLOAD
            return
        ptx.txid, ptx.channel, ptx.creator = ch.tx_id, ch.channel_id, sh.creator

        if ch.type == common_pb2.HeaderType.CONFIG:
            # config txs go to the config machinery, not the
            # endorsement pipeline (v20/validator.go:397-419): the
            # creator signature still rides the block's signature
            # batch; structure + policy checks happen in
            # _validate_config after phase 1a.
            ptx.is_config = True
            if block.header.number == 0:
                return  # genesis: trust anchor, no creator check
            try:
                ident = self.msp.deserialize_identity(sh.creator)
                if not ident.is_valid:
                    raise ValueError("invalid creator identity")
                item = _sig_item(ident, env.payload, env.signature)
            except Exception:
                ptx.code = C.BAD_CREATOR_SIGNATURE
                return
            ptx.creator_item_idx = items.add_slow(item)
            return
        if ch.type != common_pb2.HeaderType.ENDORSER_TRANSACTION:
            ptx.code = C.UNKNOWN_TX_TYPE
            return
        # txid binding: tx_id must equal sha256(nonce ‖ creator) —
        # prevents txid squatting / DUPLICATE_TXID poisoning
        # (protoutil/proputils.go:362 CheckTxID)
        if not ch.tx_id or ch.tx_id != protoutil.compute_tx_id(
            sh.nonce, sh.creator
        ):
            ptx.code = C.BAD_PROPOSAL_TXID
            return
        # dup txid in-block (v20/validator.go:460-481); the
        # vs-ledger check happens in validate() — preprocess() must
        # be runnable BEFORE the previous block commits (pipeline)
        if ch.tx_id in seen_txids:
            ptx.code = C.DUPLICATE_TXID
            return
        seen_txids[ch.tx_id] = i

        # creator: deserializable, valid cert, sig over payload
        try:
            ident = self.msp.deserialize_identity(sh.creator)
        except Exception:
            ptx.code = C.BAD_CREATOR_SIGNATURE
            return
        if not ident.is_valid:
            ptx.code = C.BAD_CREATOR_SIGNATURE
            return
        item = None
        try:
            item = _sig_item(ident, env.payload, env.signature)
        except Exception:
            # identities without an EC public key (idemix anonymous
            # creators, msp/idemix.go) verify HOST-side: each
            # signature is a zero-knowledge presentation proof the
            # batch kernel has no lane for
            host_ok = False
            if ident.is_valid and not hasattr(ident, "cert"):
                try:
                    host_ok = ident.verify(env.payload, env.signature)
                except Exception:
                    host_ok = False
            if not host_ok:
                ptx.code = C.BAD_CREATOR_SIGNATURE
                return
            ptx.host_creator_ok = True
        if item is not None:
            ptx.creator_item_idx = items.add_slow(item)

        # endorsements + rwset
        try:
            _, _, cap, prp, cca = protoutil.extract_action(
                env, parsed=(payload, ch, sh)
            )
            ptx.rwset = TxRWSet.from_bytes(cca.results)
            ptx.namespaces = tuple(sorted(ptx.rwset.ns))
            prp_bytes = cap.action.proposal_response_payload
            seen_endorsers: set[bytes] = set()
            for e in cap.action.endorsements:
                # dedup by identity: a repeated endorser counts as
                # ONE signature toward the policy (reference
                # SignatureSetToValidIdentities,
                # common/policies/policy.go:360-363)
                if e.endorser in seen_endorsers:
                    continue
                try:
                    eident = self.msp.deserialize_identity(e.endorser)
                    eitem = _sig_item(eident, prp_bytes + e.endorser, e.signature)
                except Exception as exc:
                    # unparseable endorsement: contributes nothing
                    _log.debug("endorsement dropped: %s", exc)
                    continue
                seen_endorsers.add(e.endorser)
                ptx.endo_item_idx.append(items.add_slow(eitem))
                ptx.endorsements.append((e.endorser, eident))
        except protoutil.TxParseError as e:
            ptx.code = e.code
            return
        except Exception:
            ptx.code = C.BAD_RWSET
            return

    def _py_rwset(self, ptx, native) -> None:
        """Python rwset parse for one native-fast tx the flat path
        cannot cover — identical verdicts to the pure-Python path."""
        try:
            results = native.span(native.results_span, ptx.idx) or b""
            ptx.rwset = TxRWSet.from_bytes(results)
            ptx.namespaces = tuple(sorted(ptx.rwset.ns))
        except Exception:
            ptx.code = C.BAD_RWSET

    @staticmethod
    def _fast_ctx(native) -> dict:
        """Hoist the native arrays the per-tx loop touches into plain
        Python lists ONCE per block — numpy scalar indexing inside a
        1000-iteration loop costs more than the work it guards."""
        return {
            "native": native,
            "blob": native.blob,
            "ok": native.ok.tolist(),
            "txid": native.txid_span.tolist(),
            "channel": native.channel_span.tolist(),
            "txid_digest": [bytes(d).hex() for d in native.txid_digest],
            "creator_sig_ok": native.creator_sig_ok.tolist(),
            "endo_start": native.endo_start.tolist(),
            "endo_count": native.endo_count.tolist(),
            "e_ok": native.e_ok.tolist(),
            "c_arrs": (native.payload_digest, native.creator_r,
                       native.creator_s),
            "e_arrs": (native.e_digest, native.e_r, native.e_s),
            # interned identities: resolved (deserialized + EC-checked)
            # at most ONCE per distinct cert in the block
            "creator_uid": native.creator_uid.tolist(),
            "e_uid": native.e_uid[:].tolist(),
            "e_dup": native.e_dup.tolist(),
            "ident_span": native.ident_span,
            "idents": [None] * native.n_ids,
        }

    def _resolve_uid(self, ctx, uid: int):
        """uid → (Identity | None, serialized bytes, has_ec_key)."""
        pool = ctx["idents"]
        got = pool[uid]
        if got is None:
            span = ctx["ident_span"]
            o, ln = int(span[uid, 0]), int(span[uid, 1])
            ser = ctx["blob"][o:o + ln]
            try:
                ident = self.msp.deserialize_identity(ser)
            except Exception:
                got = (None, ser, False)
            else:
                try:
                    ident.public_numbers
                    got = (ident, ser, True)
                except Exception:
                    got = (ident, ser, False)
            pool[uid] = got
        return got

    def _parse_fast(self, i: int, ctx, txs, items, seen_txids) -> bool:
        """Native-pre-parsed endorser tx → ParsedTx + signature items;
        check order mirrors the Python path exactly.  Returns False
        (after unwinding its partial state) when the envelope needs the
        Python path after all — anonymous-credential creators have no
        DER signature for the native splitter."""
        ptx = ParsedTx(idx=i)
        txs.append(ptx)
        blob = ctx["blob"]
        to, tl = ctx["txid"][i]
        ho, hl = ctx["channel"][i]
        txid_b = blob[to:to + tl] if to >= 0 else None
        ptx.txid = txid_b.decode("utf-8", "replace") if txid_b else ""
        ptx.channel = (
            blob[ho:ho + hl].decode("utf-8", "replace") if ho >= 0 else ""
        )

        # txid binding: tx_id == sha256(nonce ‖ creator) hex
        if not ptx.txid or ptx.txid != ctx["txid_digest"][i]:
            ptx.code = C.BAD_PROPOSAL_TXID
            return True
        if ptx.txid in seen_txids:
            ptx.code = C.DUPLICATE_TXID
            return True
        seen_txids[ptx.txid] = i

        cu = ctx["creator_uid"][i]
        if cu < 0:
            ptx.code = C.BAD_CREATOR_SIGNATURE
            return True
        ident, ser, has_ec = self._resolve_uid(ctx, cu)
        ptx.creator = ser
        if ident is None:
            ptx.code = C.BAD_CREATOR_SIGNATURE
            return True
        if not has_ec:
            if ident.is_valid and not hasattr(ident, "cert"):
                # idemix creator: unwind and let the Python path do the
                # host-side proof verification
                txs.pop()
                del seen_txids[ptx.txid]
                return False
            ptx.code = C.BAD_CREATOR_SIGNATURE
            return True
        if not ident.is_valid or not ctx["creator_sig_ok"][i]:
            ptx.code = C.BAD_CREATOR_SIGNATURE
            return True
        ptx.creator_item_idx = items.add_fast(ctx["c_arrs"], i, ident)

        # rwset handling is deferred: the native mvcc_prep pass after
        # the envelope loop parses all rwsets in one C call (or the
        # Python fallback parses per tx) — see _parse.  Endorser dedup
        # (policy.go:360-363) came from the C interner (e_dup).
        e_ok, e_arrs = ctx["e_ok"], ctx["e_arrs"]
        e_uid, e_dup = ctx["e_uid"], ctx["e_dup"]
        resolve = self._resolve_uid
        base = ctx["endo_start"][i]
        for j in range(base, base + ctx["endo_count"][i]):
            if not e_ok[j] or e_dup[j]:
                continue  # unparseable/duplicate contributes nothing
            uid = e_uid[j]
            if uid < 0:
                continue
            eident, eser, ehas_ec = resolve(ctx, uid)
            if eident is None or not ehas_ec:
                continue
            ptx.endo_item_idx.append(items.add_fast(e_arrs, j, eident))
            ptx.endorsements.append((eser, eident))
        return True

    # -- the pipeline ------------------------------------------------------

    def preprocess(self, block: common_pb2.Block):
        """Host parse + ASYNC device-verify launch + state-independent
        device-path inputs (policy match matrices, static MVCC arrays)
        for one block.

        Safe to run for block n+1 while block n is still committing
        (touches no ledger state): the peer's deliver loop and the
        bench overlap the host phase of the next block with the device
        phase of the current one — the TPU-shaped analog of the
        reference's deliver prefetch + validator pool overlap
        (gossip/state/state.go:540, v20/validator.go:193)."""
        self._apply_pending_knobs()
        t0 = time.perf_counter()
        txs, items, rwp, fb = self._parse(block)
        t0 = self._t("host_parse", t0)
        fetch = self._verify_launch_guarded(items)
        t0 = self._t("sig_prepare_launch", t0)
        dpre = self._device_preprocess(txs, rwp, fb)
        t0 = self._t("device_pre", t0)
        # header+data wire form for the ledger commit (the committer
        # only splices fresh metadata on — see blockstore.add_block)
        hd_bytes = protoutil.block_header_data_bytes(block)
        self._t("hd_frame", t0)
        # the MSP manager the identities were validated against: a
        # config tx in the PREVIOUS block may rotate membership between
        # preprocess and validate — validate() detects and re-parses
        return txs, items, fetch, self.msp, dpre, fb, hd_bytes

    def preprocess_many(self, blocks: list) -> list:
        """Coalesced ``preprocess`` over several in-flight blocks: each
        block parses as usual, then ALL their signature batches go up
        in ONE concatenated verify dispatch (p256.verify_launch_many),
        amortizing the ladder's dispatch latency across the blocks the
        pipeline has in flight.  Each returned tuple is a drop-in
        ``pre`` for ``validate_launch`` — the per-block VerifyHandle is
        a device-side slice with the exact lane layout a solo launch
        would produce, so stage-2 and the committer are unchanged."""
        blocks = list(blocks)
        self._apply_pending_knobs()
        if len(blocks) <= 1:
            return [self.preprocess(b) for b in blocks]
        if self.host_pool is not None:
            return self._preprocess_many_pooled(blocks)
        parsed = []
        for block in blocks:
            t0 = time.perf_counter()
            parsed.append(self._parse(block))
            self._t("host_parse", t0)
        t0 = time.perf_counter()
        fetches = self._verify_launch_many_guarded(
            [p[1] for p in parsed]
        )
        self._t("sig_prepare_launch", t0)
        out = []
        for block, (txs, items, rwp, fb), fetch in zip(
            blocks, parsed, fetches
        ):
            t0 = time.perf_counter()
            dpre = self._device_preprocess(txs, rwp, fb)
            t0 = self._t("device_pre", t0)
            hd_bytes = protoutil.block_header_data_bytes(block)
            self._t("hd_frame", t0)
            out.append((txs, items, fetch, self.msp, dpre, fb, hd_bytes))
        return out

    def _preprocess_many_pooled(self, blocks: list) -> list:
        """``preprocess_many`` with the host staging pool: every
        block's parse fans out across the workers at once, and each
        block's state-independent device preprocessing is submitted
        the moment its own parse lands — so device_pre(k) overlaps
        parse(k+1..) on the pool instead of serializing behind the
        whole parse train.  The coalesced verify staging then shards
        prepare_cols over the same pool inside verify_launch_many.

        Every task is block-local (parse builds per-block objects;
        _device_preprocess touches only its block's ParsedTx records —
        the shared plan/row caches are append-only dict memos whose
        worst concurrent case is a duplicated compute), so the pooled
        result is the serial result, pinned by the DeviceToyValidator
        battery in tests/test_multidevice.py.

        Stage timings record the CALLER's critical-path wait (the time
        the feeder actually stalls), like the pipeline's prefetch_wait;
        the per-shard work itself rides
        ``host_stage_pool_seconds{stage,worker}``."""
        pool = self.host_pool
        t0 = time.perf_counter()
        parse_futs = [
            pool.submit(self._parse, b, stage="host_parse")
            for b in blocks
        ]
        parsed, dpre_futs = [], []
        for f in parse_futs:
            p = f.result()
            parsed.append(p)
            dpre_futs.append(pool.submit(
                self._device_preprocess, p[0], p[2], p[3],
                stage="device_pre",
            ))
        self._t("host_parse", t0)
        t0 = time.perf_counter()
        fetches = self._verify_launch_many_guarded(
            [p[1] for p in parsed], pool=pool
        )
        t0 = self._t("sig_prepare_launch", t0)
        out = []
        for block, (txs, items, rwp, fb), fetch, df in zip(
            blocks, parsed, fetches, dpre_futs
        ):
            t0 = time.perf_counter()
            dpre = df.result()
            t0 = self._t("device_pre", t0)
            hd_bytes = protoutil.block_header_data_bytes(block)
            self._t("hd_frame", t0)
            out.append((txs, items, fetch, self.msp, dpre, fb, hd_bytes))
        return out

    def validate(self, block: common_pb2.Block, pre=None):
        return self.validate_finish(self.validate_launch(block, pre=pre))

    def validate_launch(
        self, block: common_pb2.Block, pre=None, overlay=None,
        extra_txids=None,
    ):
        """Run every pre-device-sync step for one block — structural
        codes, dup checks, committed-version fill, stage-2 dispatch —
        and return a PendingBlock; ``validate_finish`` syncs the device
        and produces (filter, batch, history).

        ``overlay``: the UpdateBatch of the in-flight predecessor
        WINDOW — one block's batch at pipeline depth 2, or the
        newest-wins MERGE of up to depth−1 predecessors' batches
        (``UpdateBatch.merged``) whose ledger commits may still be
        draining on the committer thread.  Its writes override
        committed-version lookups (and range re-execution, and the SBE
        metadata probes via the unioned ``has_meta``), so this block
        launches without waiting for any predecessor's fsync.
        ``extra_txids``: txids of EVERY in-flight predecessor for the
        duplicate-txid check (their block-store index inserts may not
        have landed yet).

        Pipelined callers must SERIALIZE around blocks that rotate
        validation inputs — config blocks (MSP/policy object rotation)
        and blocks writing the ``_lifecycle`` namespace (state-backed
        chaincode definitions feed the preprocess-time policy plans):
        commit such a predecessor fully, then launch with overlay=None.
        Launching with a lifecycle-writing overlay raises — a stale
        plan here would fork a pipelined peer from a serial one."""
        if overlay is not None and any(
            k[0] == "_lifecycle" for k in overlay.updates
        ):
            raise ValueError(
                "pipelined launch across a lifecycle-writing block: "
                "commit the predecessor before launching this block"
            )
        if pre is None:
            pre = self.preprocess(block)
        if pre[3] is not self.msp or (
            pre[4] is not None and pre[4].policies is not self.policies
        ):
            # membership or policy tree rotated after this block was
            # preprocessed (committed config tx): stale identity
            # validations / plans must not leak — redo the parse
            pre = self.preprocess(block)
        txs, items, fetch, _, dpre, fb = pre[:6]
        hd_bytes = pre[6] if len(pre) > 6 else None
        # parsed records for post-commit consumers (config rotation) —
        # the commit path is serialized per channel, so this is safe
        self.last_parsed = txs

        # dup txid vs committed ledger + in-flight predecessors
        # (deferred from preprocess).  fb.codes is kept in sync — the
        # vectorized state_fill reads it as the live verdict array.
        if self.blocks is not None or extra_txids:
            for ptx in txs:
                if ptx.undetermined and not ptx.is_config and (
                    (extra_txids is not None and ptx.txid in extra_txids)
                    or (self.blocks is not None
                        and self.blocks.tx_exists(ptx.txid))
                ):
                    ptx.code = C.DUPLICATE_TXID
                    if fb is not None:
                        fb.codes[ptx.idx] = int(C.DUPLICATE_TXID)

        pending = PendingBlock(
            block=block, txs=txs, items=items, fetch=fetch, dpre=dpre,
            overlay=overlay, fb=fb, hd_bytes=hd_bytes,
        )
        # fused single-sync device path: policy + MVCC consume the
        # verify output ON DEVICE (one dispatch + one readback per
        # block); falls back to the host path for custom plugins,
        # non-v3 kernels, consumption-unsafe blocks, or key-level
        # endorsement (the SBE launch veto — committed key policies may
        # have landed AFTER this block was preprocessed)
        if (
            getattr(fetch, "device_out", None) is not None and txs and dpre
            and not self._sbe_launch_veto(txs, dpre, overlay)
        ):
            try:
                pending.fetch2, pending.range_phantom = self._launch_device(
                    block, txs, fetch, dpre, overlay, fb=fb
                )
            except Exception as e:
                # fused stage-2 dispatch died: with a lane guard this
                # block degrades to the host MVCC path (fetch2 stays
                # None) instead of tearing the stream down
                if self.device_guard is None:
                    raise
                self.device_guard.record_failure(e)
                _log.warning(
                    "fused stage-2 dispatch failed (%s) — block %d "
                    "takes the host path", e, block.header.number,
                )
        return pending

    def _sbe_launch_veto(self, txs, dpre, overlay) -> bool:
        """True when a written key of this block carries a key-level
        endorsement policy in committed state (or the in-flight
        predecessor's batch) — the device program has no SBE lanes, so
        such blocks re-route to the host dispatch path.  Free on
        channels that never set validation parameters (meta_count 0).
        In-block metadata WRITES never reach here: the native parser
        routes them off the flat path and the group builders return
        None for them at preprocess."""
        if not self._metaful(overlay):
            return False
        static = dpre.static
        if dpre.rwp is not None and getattr(static, "u_pairs", None):
            rwp = dpre.rwp
            for u in np.unique(rwp.w_uid[:rwp.n_writes]).tolist():
                ns, key = static.u_pairs[u]
                if self._committed_key_has_meta(ns, key, overlay):
                    return True
            return False
        for ptx in txs:
            if not ptx.undetermined or ptx.is_config or ptx.rwset is None:
                continue
            for ns_name, n in ptx.rwset.ns.items():
                for k in n.writes:
                    if self._committed_key_has_meta(ns_name, k, overlay):
                        return True
        return False

    def validate_finish(self, pending: "PendingBlock"):
        """Sync the device stage-2 of a launched block and produce the
        (filter, batch, history) triple.  With a device-lane guard, a
        stage-2 sync failure degrades THIS block to the host path (the
        guarded verify handle re-verifies on CPU if the device output
        is gone too) and counts toward the degraded latch."""
        if pending.fetch2 is not None:
            self._last_device_sync_s = 0.0
            try:
                result = self._finish_device(pending)
            except Exception as e:
                if self.device_guard is None:
                    raise
                self.device_guard.record_failure(e)
                _log.warning(
                    "device stage-2 sync failed (%s) — block %d "
                    "re-validating on the host path", e,
                    pending.block.header.number,
                )
                result = None
            else:
                if result is not None and self.device_guard is not None:
                    # only the fetch2() sync is the lane's latency —
                    # the host postprocess after it must not trip a
                    # deadline tuned for the device
                    if not self.device_guard.check_deadline(
                        self._last_device_sync_s
                    ):
                        self.device_guard.record_success()
            if result is not None:
                return result
        return self._validate_host(
            pending.block, pending.txs, pending.items, pending.fetch,
            overlay=pending.overlay, fb=pending.fb,
        )

    def _validate_host(self, block, txs, items, fetch, overlay=None,
                       fb=None):
        # a columnar parse leaves endorsement lists / namespaces lazy:
        # the host dispatch path walks them, so fill them first
        self._materialize_for_host(txs, fb)
        # phase 1a: one batched ECDSA verify for the whole block —
        # the host path's ONE intended device sync
        t0 = time.perf_counter()
        sig_valid = (
            np.asarray(fetch(), bool)  # fabtpu: noqa(FT003)
            if items else np.zeros(0, bool)
        )
        self._t("device_wait", t0)

        for ptx in txs:
            if ptx.undetermined and ptx.creator_item_idx >= 0:
                if not sig_valid[ptx.creator_item_idx]:
                    ptx.code = C.BAD_CREATOR_SIGNATURE

        # config txs: structural + signature + config-machinery checks
        # (v20/validator.go:397-419 — never rubber-stamped)
        for ptx in txs:
            if ptx.is_config and ptx.undetermined:
                ptx.code = self._validate_config(block, ptx)

        # phase 1b: per-namespace plugin dispatch (policy reduction).
        # A tx is valid only if EVERY written namespace's plugin
        # approves it (plugindispatcher/dispatcher.go:190-217).
        ctx = BlockValidationCtx(
            txs=txs, sig_valid=sig_valid, msp_manager=self.msp,
            policy_provider=self.policies,
        )
        by_plugin: dict[str, list[tuple[ParsedTx, tuple]]] = {}
        for ptx in txs:
            if not ptx.undetermined or ptx.is_config:
                continue
            infos = [self.policies.info(ns) for ns in ptx.namespaces]
            if not ptx.namespaces or any(i is None for i in infos):
                ptx.code = C.INVALID_CHAINCODE
                continue
            for ns, info in zip(ptx.namespaces, infos):
                name = info.plugin or "default"
                by_plugin.setdefault(name, []).append((ptx, ns))
        # key-level (state-based) endorsement: when any written key
        # carries a committed VALIDATION_PARAMETER — or any tx writes
        # one — the namespace verdicts become per-key fallbacks inside
        # the SBE pass instead of immediate failures
        sbe_active = self._sbe_active(txs, overlay)
        ns_verdicts: dict | None = {} if sbe_active else None
        for name, group in by_plugin.items():
            plug = self.plugins.get(name)
            if plug is None:
                for ptx, _ in group:
                    ptx.code = C.INVALID_OTHER_REASON
                continue
            if hasattr(plug, "validate_batch_group"):
                ok = plug.validate_batch_group(ctx, group)
            else:
                # legacy SPI returns [T] per-tx verdicts; realign to the
                # per-(tx, namespace) group entries by block position
                per_tx = plug.validate_batch(ctx)
                ok = [per_tx[ptx.idx] for ptx, _ in group]
            for (ptx, ns), good in zip(group, ok):
                if ns_verdicts is not None:
                    ns_verdicts[(ptx.idx, ns)] = bool(good)
                elif not good and ptx.undetermined:
                    ptx.code = C.ENDORSEMENT_POLICY_FAILURE
        if sbe_active:
            self._sbe_pass(txs, sig_valid, ns_verdicts, overlay)

        # phase 2: MVCC over the whole block
        mvcc_txs, committed = self._mvcc_inputs(txs, overlay=overlay)
        pre_ok = np.array([ptx.undetermined for ptx in txs], bool)
        if txs:
            valid, conflict, phantom = mvcc_ops.mvcc_validate_block(
                mvcc_txs, committed, pre_ok
            )
            for ptx, v, ph in zip(txs, valid, phantom):
                if not ptx.undetermined:
                    continue
                if v:
                    ptx.code = C.VALID
                else:
                    ptx.code = C.PHANTOM_READ_CONFLICT if ph else C.MVCC_READ_CONFLICT

        # phase 3: filter + update batch + history
        tx_filter = bytes(ptx.code for ptx in txs)
        batch, history = self._build_updates(
            block.header.number, txs, overlay=overlay, sbe=sbe_active
        )
        return tx_filter, batch, history

    # -- state-based (key-level) endorsement -------------------------------

    def _sbe_active(self, txs, overlay=None) -> bool:
        """True when key-level endorsement applies to this block:
        some tx writes key metadata, or a written key carries a
        committed (or in-flight predecessor) VALIDATION_PARAMETER.
        The committed probe only runs when the state reports any
        metadata at all (statedb.meta_count) — channels that never use
        SetStateValidationParameter pay nothing."""
        metaful = self._metaful(overlay)
        for ptx in txs:
            rw = ptx._rwset  # lazy rwsets (columnar) can't carry them
            if rw is None:
                if not metaful:
                    continue
                rw = ptx.rwset  # forces the parse only on SBE channels
                if rw is None:
                    continue
            for ns_name, n in rw.ns.items():
                if n.metadata_writes:
                    return True
                if metaful:
                    for k in n.writes:
                        # ANY committed metadata (not just a policy)
                        # activates the pass: plain value writes must
                        # PRESERVE existing metadata, which the fast
                        # update builder doesn't look up
                        if self._committed_key_has_meta(
                            ns_name, k, overlay
                        ):
                            return True
        return False

    def _metaful(self, overlay) -> bool:
        """Any key metadata anywhere the block could see it: committed
        state (meta_count) or the in-flight predecessor's batch."""
        return getattr(self.state, "meta_count", 0) > 0 or (
            overlay is not None and getattr(overlay, "has_meta", False)
        )

    def _committed_key_has_meta(self, ns: str, key: str, overlay) -> bool:
        if overlay is not None:
            vv = overlay.updates.get((ns, key))
            if vv is not None:
                return bool(vv.value is not None and vv.metadata)
        vv = self.state.get_state(ns, key)
        return vv is not None and bool(vv.metadata)

    def _committed_key_policy(self, ns: str, key: str, overlay):
        """Committed VALIDATION_PARAMETER bytes for (ns, key) — the
        in-flight predecessor's update batch overrides the state read
        (same serialization argument as _committed_versions)."""
        from fabric_tpu.ledger.rwset import (
            VALIDATION_PARAMETER, decode_metadata,
        )

        if overlay is not None:
            vv = overlay.updates.get((ns, key))
            if vv is not None:
                if vv.value is None or not vv.metadata:
                    return None
                return decode_metadata(vv.metadata).get(VALIDATION_PARAMETER)
        vv = self.state.get_state(ns, key)
        if vv is None or not vv.metadata:
            return None
        return decode_metadata(vv.metadata).get(VALIDATION_PARAMETER)

    def _sbe_pass(self, txs, sig_valid, ns_verdicts, overlay) -> None:
        """Key-level endorsement enforcement, in block order — the
        reference's dependency-managed walk
        (statebased/validator_keylevel.go:244-260 + the
        vpmanagerimpl.go:47-199 waits) collapsed to a serial pass: a
        tx's written keys are checked under the policies in effect AT
        ITS POSITION, where 'in effect' folds in metadata updates from
        earlier PLUGIN-valid txs of the same block (matching the
        reference: an earlier tx later killed by MVCC still had its
        update visible to the key-level validator).  Keys without a
        key-level policy fall back to the namespace verdict; a tx whose
        namespace has no written keys at all is judged by the
        namespace policy alone."""
        from fabric_tpu.ledger.rwset import VALIDATION_PARAMETER

        pending: dict = {}    # (ns, key) → policy bytes | None (cleared)
        pol_cache: dict = {}  # policy bytes → (ast, plan) | None
        comm_cache: dict = {}  # (ns, key) → committed policy probe
        for ptx in txs:
            if not ptx.undetermined or ptx.is_config or ptx.rwset is None:
                continue
            tx_ok = True
            for ns_name in ptx.namespaces:
                n = ptx.rwset.ns.get(ns_name)
                if n is None:
                    continue
                keys = sorted(set(n.writes) | set(n.metadata_writes))
                if not keys:
                    if not ns_verdicts.get((ptx.idx, ns_name), False):
                        tx_ok = False
                        break
                    continue
                for k in keys:
                    if (ns_name, k) in pending:
                        pb = pending[(ns_name, k)]
                    elif (ns_name, k) in comm_cache:
                        pb = comm_cache[(ns_name, k)]
                    else:
                        pb = comm_cache[(ns_name, k)] = (
                            self._committed_key_policy(ns_name, k, overlay)
                        )
                    if pb is None:
                        ok_k = ns_verdicts.get((ptx.idx, ns_name), False)
                    else:
                        ok_k = self._eval_key_policy(
                            pb, ptx, sig_valid, pol_cache
                        )
                    if not ok_k:
                        tx_ok = False
                        break
                if not tx_ok:
                    break
            if not tx_ok:
                ptx.code = C.ENDORSEMENT_POLICY_FAILURE
                continue
            # plugin-valid: this tx's metadata updates take effect for
            # every later tx in the block
            for ns_name, n in ptx.rwset.ns.items():
                for k, entries in n.metadata_writes.items():
                    pending[(ns_name, k)] = entries.get(VALIDATION_PARAMETER)

    def _eval_key_policy(self, policy_bytes, ptx, sig_valid, cache) -> bool:
        """Evaluate one key-level policy over the tx's sig-valid
        endorsements (the exact interpreter — key policies are rare
        and arbitrary, so no batch plan reuse is assumed)."""
        got = cache.get(policy_bytes, False)
        if got is False:
            try:
                from fabric_tpu.crypto.msp import policy_from_proto
                from fabric_tpu.protos import policies_pb2

                env = protoutil.unmarshal(
                    policies_pb2.SignaturePolicyEnvelope, policy_bytes
                )
                ast = policy_from_proto(env)
                plan = pol.compile_plan(ast)
                got = (ast, plan)
            except Exception:
                got = None  # unparseable policy: fail closed
            cache[policy_bytes] = got
        if got is None:
            return False
        ast, plan = got
        if not ptx.endorsements:
            return False
        idents = [ident for (_, ident) in ptx.endorsements]
        valid = np.array(
            [bool(sig_valid[i]) for i in ptx.endo_item_idx], bool
        )
        m = pol.match_matrix(idents, plan.principals) & valid[:, None]
        return bool(pol.evaluate(ast, m))

    # -- fused single-sync device path ------------------------------------

    def _put_group(self, gp):
        """Upload one policy-group pack (prefetch thread) under the
        ``policy_table`` partition rule when a mesh is configured.
        The bytes count on the launch ledger's ``stage2_prefetch``
        h2d lane — prefetch-thread uploads are device transfer time
        the launch-time accounting would otherwise miss."""
        import jax.numpy as jnp

        from fabric_tpu.observe import ledger as _ledger

        _ledger.note_h2d("stage2_prefetch", gp.nbytes)
        if self.mesh is None:
            return jnp.asarray(gp)
        from fabric_tpu.parallel.mesh import shard

        return shard(self.mesh, "policy_table", jnp.asarray(gp))

    def _device_preprocess(self, txs, rwp=None, fb=None):
        """State-INDEPENDENT device-path inputs: policy match matrices
        (vectorized gather over per-identity cached principal rows) and
        static MVCC arrays.  Runs in the prefetch thread, overlapping
        the previous block's device time; returns None when the block
        needs the host dispatch path (custom plugins).  When the native
        mvcc_prep covered every undetermined endorser tx (``rwp``),
        the static arrays come from numpy scatters over its flat
        output instead of per-read Python loops."""
        from fabric_tpu.ops import mvcc as mvcc_ops
        from fabric_tpu.utils.batching import next_pow2

        if not txs or p256._KERNEL in ("v1", "v2"):
            return None  # fused device path requires the v3 kernel
        default = self.plugins.get("default")
        if type(default).__name__ != "DefaultValidation":
            return None
        if fb is not None:
            dp = self._device_pre_columnar(txs, rwp, fb)
            if dp is not NotImplemented:
                return dp
            # block mixes lanes the columnar builder doesn't carry
            # (idemix creators, range queries, partial native parses):
            # materialize the per-tx lists and run the generic builder
            self._materialize_for_host(txs, fb)

        entries = []  # (ptx, ns, info)
        for ptx in txs:
            if not ptx.undetermined or ptx.is_config:
                continue
            if ptx.rwset is not None and any(
                n.metadata_writes for n in ptx.rwset.ns.values()
            ):
                # key-level endorsement rides this block: the device
                # program has no SBE lanes → host dispatch path
                return None
            infos = [self.policies.info(ns) for ns in ptx.namespaces]
            if not ptx.namespaces or any(i is None for i in infos):
                ptx.code = C.INVALID_CHAINCODE  # same verdict on both paths
                continue
            if any((i.plugin or "default") != "default" for i in infos):
                return None  # custom plugin in play → host dispatch path
            for ns, info in zip(ptx.namespaces, infos):
                entries.append((ptx, ns, info))

        # policy groups (by policy object), padded to buckets; match
        # rows built once per distinct identity then gathered
        by_policy: dict[int, list] = {}
        plans: dict[int, object] = {}
        for ptx, ns, info in entries:
            key = id(info.policy)
            if key not in plans:
                plans[key] = default._plan(info.policy)
            by_policy.setdefault(key, []).append((ptx, info))
        groups = []
        group_entries = []
        for key, ents in by_policy.items():
            plan = plans[key]
            P = len(plan.principals)
            S = max(4, next_pow2(max(
                (len(p.endorsements) for p, _ in ents), default=1) or 1))
            E = max(16, next_pow2(len(ents)))
            pool_rows = [np.zeros(P, bool)]  # row 0 = padding (no match)
            pool_of: dict[int, int] = {}
            idx_mat = np.zeros((E, S), np.int32)
            endo_idx = np.full((E, S), -1, np.int32)
            tx_of = np.full(E, -1, np.int32)
            for e, (ptx, info) in enumerate(ents):
                tx_of[e] = ptx.idx
                if ptx.endo_item_idx:
                    endo_idx[e, : len(ptx.endo_item_idx)] = ptx.endo_item_idx
                for s, (ser, ident) in enumerate(ptx.endorsements):
                    pi = pool_of.get(id(ident))
                    if pi is None:
                        pi = pool_of[id(ident)] = len(pool_rows)
                        pool_rows.append(default._match_row(plan, ser, ident))
                    idx_mat[e, s] = pi
            match = np.stack(pool_rows)[idx_mat]  # [E, S, P] gather
            # pack + upload NOW (prefetch thread): launch-time H2D over
            # the tunnel is latency-bound and sits on the critical path
            gp = np.empty((E, S * P + S + 1), np.int32)
            gp[:, :S * P] = match.reshape(E, -1)
            gp[:, S * P:S * P + S] = endo_idx
            gp[:, -1] = tx_of
            groups.append((plan, self._put_group(gp), E, S))
            group_entries.append(ents)

        # static MVCC arrays (committed-version fill deferred to
        # validate time — it needs the predecessor's state commit)
        flat_ok = rwp is not None and all(
            (not ptx.undetermined) or ptx.is_config
            or rwp.status[ptx.idx] == 0
            for ptx in txs
        )
        if flat_ok:
            ns_names = rwp.ns_names()
            ukeys = rwp.ukey_strs()
            composite = [
                ("pub", ns_names[rwp.ns_of_ukey[u]], ukeys[u])
                for u in range(rwp.n_keys)
            ]
            static = mvcc_ops.prepare_block_from_flat(len(txs), rwp, composite)
            static.u_pairs = [(c[1], c[2]) for c in composite]
            # key → unique-id index for the launch-time overlay
            # overrides — built HERE (prefetch thread) so the caller
            # thread's state_fill never pays the dict construction
            static.u_index = dict(zip(static.u_pairs,
                                      range(rwp.n_keys)))
            static.packed_static()
            if self.resident is not None:
                # expected-read plane for the device-resident compare:
                # state-independent, so it uploads HERE (prefetch
                # thread), never on the launch critical path
                static.packed_read_pv()
            return _DevicePre(
                groups=groups, group_entries=group_entries, static=static,
                has_range=False, policies=self.policies,
                rwp=rwp, ns_names=ns_names, ukeys=ukeys,
            )
        mvcc_txs = []
        has_range = False
        for ptx in txs:
            if ptx.rwset is None or not ptx.undetermined:
                mvcc_txs.append(
                    mvcc_ops.TxRWSet(reads=[], writes=[], range_reads=[])
                )
                continue
            if any(n.range_queries for n in ptx.rwset.ns.values()):
                has_range = True
            reads, writes, rqs = ptx.rwset.mvcc_form()
            mvcc_txs.append(
                mvcc_ops.TxRWSet(reads=reads, writes=writes, range_reads=rqs)
            )
        static = mvcc_ops.prepare_block_static(mvcc_txs, bucketed=True)
        static.packed_static()
        return _DevicePre(
            groups=groups, group_entries=group_entries, static=static,
            has_range=has_range, policies=self.policies,
        )

    def _device_pre_columnar(self, txs, rwp, fb):
        """Policy-group + static-MVCC construction straight from the
        columnar arrays: match matrices come from a per-identity row
        pool gathered through the [n,S] uid matrix, entries from the
        flat (tx, ns) arrays — no per-entry Python loop.  Handles only
        blocks whose every live tx is a flat-rwset columnar tx;
        returns NotImplemented otherwise (caller falls back to the
        generic builder), or None for custom plugins (host path)."""
        from fabric_tpu.ops import mvcc as mvcc_ops
        from fabric_tpu.utils.batching import next_pow2

        if rwp is None:
            return NotImplemented
        default = self.plugins["default"]
        n = len(txs)
        codes = fb.codes
        NOTV = int(C.NOT_VALIDATED)
        live = (codes == NOTV) & ~fb.is_config
        st_ok = rwp.status[:n] == 0
        if bool((live & ~(fb.c_ok & st_ok)).any()) or not live.any():
            return NotImplemented

        tns_c = rwp.tx_ns_count[:n]
        # a tx writing no namespace → INVALID_CHAINCODE (same verdict
        # as the host dispatch path's entry collection)
        zero = live & (tns_c == 0)
        if zero.any():
            for i in np.flatnonzero(zero).tolist():
                txs[i].code = C.INVALID_CHAINCODE
                codes[i] = int(C.INVALID_CHAINCODE)
            live = live & ~zero
            if not live.any():
                return NotImplemented

        total_ns = int(tns_c.sum())
        etx = np.repeat(np.arange(n), tns_c)
        ens = rwp.ns_ids_flat[:total_ns]
        sel = live[etx]
        etx, ens = etx[sel], ens[sel]
        ns_names = rwp.ns_names()
        infos = [self.policies.info(nm) for nm in ns_names]
        bad_ids = [j for j, inf in enumerate(infos) if inf is None]
        if bad_ids:
            badsel = np.isin(ens, bad_ids)
            bad_txs = np.unique(etx[badsel])
            for i in bad_txs.tolist():
                txs[i].code = C.INVALID_CHAINCODE
                codes[i] = int(C.INVALID_CHAINCODE)
            keep = ~np.isin(etx, bad_txs)
            etx, ens = etx[keep], ens[keep]
        if any(
            inf is not None and (inf.plugin or "default") != "default"
            for inf in infos
        ):
            return None  # custom plugin in play → host dispatch path

        key_ns: dict[int, list] = {}
        key_info: dict[int, object] = {}
        for j, inf in enumerate(infos):
            if inf is None:
                continue
            key = id(inf.policy)
            key_ns.setdefault(key, []).append(j)
            key_info[key] = inf
        groups = []
        group_entries = []
        S = fb.uid_mat.shape[1]
        n_pool = len(fb.idents)
        for key, ns_ids in key_ns.items():
            inf = key_info[key]
            plan = default._plan(inf.policy)
            P = len(plan.principals)
            if len(key_ns) > 1:
                gtx = etx[np.isin(ens, ns_ids)]
            else:
                gtx = etx
            E = len(gtx)
            Eb = max(16, next_pow2(max(E, 1)))
            row_pool = np.zeros((n_pool + 1, P), bool)
            for u in range(n_pool):
                if fb.has_ec[u]:
                    row_pool[u + 1] = default._match_row(
                        plan, fb.sers[u], fb.idents[u]
                    )
            gp = np.zeros((Eb, S * P + S + 1), np.int32)
            gp[:, S * P:S * P + S] = -1
            gp[:, -1] = -1
            if E:
                gp[:E, :S * P] = row_pool[fb.uid_mat[gtx]].reshape(E, -1)
                gp[:E, S * P:S * P + S] = fb.endo_idx_mat[gtx]
                gp[:E, -1] = gtx
            # ONE packed upload per group (prefetch thread)
            groups.append((plan, self._put_group(gp), Eb, S))
            group_entries.append(range(E))

        ukeys = rwp.ukey_strs()
        ns_of = rwp.ns_of_ukey[:rwp.n_keys].tolist()
        pairs = [(ns_names[ns_of[u]], ukeys[u]) for u in range(rwp.n_keys)]
        composite = [("pub", ns, k) for ns, k in pairs]
        static = mvcc_ops.prepare_block_from_flat(n, rwp, composite)
        static.u_pairs = pairs
        # prefetch-thread key index (see _device_preprocess)
        static.u_index = dict(zip(pairs, range(rwp.n_keys)))
        static.packed_static()  # ONE H2D, prefetch thread
        if self.resident is not None:
            static.packed_read_pv()  # resident-compare expected plane
        return _DevicePre(
            groups=groups, group_entries=group_entries, static=static,
            has_range=False, policies=self.policies,
            rwp=rwp, ns_names=ns_names, ukeys=ukeys,
            codes_synced=True,
        )

    def _launch_device(self, block, txs, handle, dpre, overlay=None,
                       fb=None):
        """Host-side device-path launch: range re-execution, structural
        arrays, committed-version fill (+ overlay), stage-2 dispatch.
        Returns the packed-output fetch.

        The ``state_fill`` stage here is fully vectorized for columnar
        blocks (``fb`` with codes kept in sync by the columnar builder
        — ``dpre.codes_synced``): the per-tx structural/creator loop
        becomes numpy masks over the live code array, and the
        committed-version fill is one fused backend column gather
        (``statedb.get_versions_cols``) with overlay overrides applied
        by iterating the (small) overlay instead of probing it per
        unique key."""
        from fabric_tpu.peer.device_block import DeviceBlockPipeline

        t0 = time.perf_counter()
        # committed-range phantom re-execution (host state reads, plus
        # the in-flight predecessor's writes when pipelined).  The CODE
        # is assigned at finish, AFTER the policy verdicts — the host
        # path's check order is creator → policy → mvcc/phantom, and a
        # tx failing both must report ENDORSEMENT_POLICY_FAILURE on
        # both paths; here the tx is only excluded from the kernel's
        # writer set (its writes must not kill other reads).
        range_phantom: set = set()
        if dpre.has_range:
            for ptx in txs:
                if (
                    ptx.undetermined and not ptx.is_config
                    and ptx.rwset is not None
                    and (self._committed_range_phantom(ptx, overlay)
                         or (overlay is not None
                             and _overlay_range_phantom(ptx, overlay)))
                ):
                    range_phantom.add(ptx.idx)

        t_bucket = int(dpre.static.read_keys.shape[0])
        structural = np.zeros(t_bucket, bool)
        creator_idx = np.full(t_bucket, -1, np.int32)
        if (fb is not None and getattr(dpre, "codes_synced", False)
                and not dpre.has_range):
            # columnar fast lane: fb.codes IS the live verdict array
            # (the columnar builder and the dup check keep it synced),
            # every live tx is a flat columnar endorser tx (no idemix
            # -2 lanes, no range phantoms) — two masked assignments
            # replace the 1000-iteration Python loop
            n = len(txs)
            live = (fb.codes == int(C.NOT_VALIDATED)) & ~fb.is_config
            structural[:n] = live
            creator_idx[:n] = np.where(live, fb.creator_item, -1)
        else:
            for ptx in txs:
                if ptx.undetermined and not ptx.is_config:
                    structural[ptx.idx] = ptx.idx not in range_phantom
                    creator_idx[ptx.idx] = (
                        -2 if ptx.host_creator_ok else ptx.creator_item_idx
                    )  # -2 = host-verified (idemix) → always-true lane

        static = dpre.static
        resident_pack = None
        if (self.resident is not None and self.resident.enabled
                and getattr(static, "u_pairs", None) is not None
                and not dpre.has_range):
            # device-resident state path: the committed-version
            # compare runs ON DEVICE against the resident table; the
            # host gather below shrinks to the miss/overlay set.  Any
            # failure latches the cache off and this block (and every
            # later one) takes the host oracle path — verdicts never
            # change, only time does.
            try:
                resident_pack = self._resident_pack(static, overlay)
            except Exception as e:
                self.resident.disable(f"resident launch failed: {e}")
                _log.warning(
                    "resident state path failed for block %d (%s) — "
                    "falling back to host state_fill",
                    block.header.number, e,
                )
                resident_pack = None
        if resident_pack is not None:
            ver_ok = 1  # inert lane: computed on device from the table
        elif getattr(static, "u_pairs", None) is not None:
            # flat path: committed versions per UNIQUE key, compared on
            # host — one [T] bool rides to the device
            ver_ok = self._flat_ver_ok(static, overlay)
        else:
            committed = self._committed_versions(
                static.read_key_set, overlay=overlay
            )
            ver_ok = static.host_ver_ok(committed)
        # ONE launch-time H2D: creator_idx | structural | ver_ok
        launch_vec = np.empty((t_bucket, 3), np.int32)
        launch_vec[:, 0] = creator_idx
        launch_vec[:, 1] = structural
        launch_vec[:, 2] = ver_ok
        t0 = self._t("state_fill", t0)

        if self._device_pipeline is None:
            self._device_pipeline = DeviceBlockPipeline()
        _faults.fire("validator.stage2")  # chaos hook (no-op unarmed)
        fetch2 = self._device_pipeline.run(
            handle, launch_vec, dpre.groups, static.packed_static(),
            static.dims, t_bucket, mesh=self.mesh,
            resident=resident_pack,
        )
        self._t("stage2_dispatch", t0)
        return fetch2, range_phantom

    # -- device-resident state (fabric_tpu/state) --------------------------

    def _resident_pack(self, static, overlay):
        """Build the resident-state stage-2 operands for one flat
        block — ``(table_snapshot, u_pack [Ub,4] i32, read_pv_dev)``
        — or None when the block must take the host oracle path.  The
        slot/host-lane packing (hit slots captured atomically with
        the table snapshot, misses host-gathered + admitted, overlay
        keys forced onto overlay-valued host lanes) is the
        subsystem's ``state.build_launch_pack``; this wrapper only
        supplies the prefetch-built key index and appends the
        expected-read plane the prefetch thread already uploaded."""
        from fabric_tpu.state import build_launch_pack

        pairs = static.u_pairs
        idx = getattr(static, "u_index", None)
        if idx is None:  # built on the prefetch thread normally
            idx = static.u_index = dict(zip(pairs, range(len(pairs))))
        out = build_launch_pack(
            self.resident, pairs, self.state, overlay=overlay,
            u_index=idx,
        )
        if out is None:
            return None
        table, u_pack = out
        return (table, u_pack, static.packed_read_pv())

    def resident_commit(self, batch) -> None:
        """Apply one COMMITTED block's write-set to the resident
        version table as a delta scatter — called at the commit
        boundary by the CommitPipeline (committer thread; inline for
        barriers/serial) and by the serial ``commit_block`` path, so
        the table never misses a committed delta regardless of which
        path a block rode.  Idempotent (a replayed batch scatters the
        same values); a device failure latches the cache off, never
        changes verdicts.  No-op when the cache is off or disabled."""
        res = self.resident
        if res is None or not res.enabled or batch is None:
            return
        try:
            res.apply_batch(batch)
        except Exception as e:
            res.disable(f"commit scatter failed: {e}")
            _log.warning(
                "resident commit scatter failed (%s) — cache disabled, "
                "blocks take the host state_fill path", e,
            )

    def _flat_ver_ok(self, static, overlay):
        """[T] bool committed-version check for a flat block: one FUSED
        column gather over the UNIQUE read keys (the
        preLoadCommittedVersionOfRSet analog —
        ``statedb.get_versions_cols`` fills the arrays in a single
        backend pass, no dict round-trip), overlay overrides for the
        in-flight predecessor window applied by walking the overlay's
        (small) write set against the prefetch-built key index instead
        of probing the overlay once per unique key, then a vectorized
        per-read compare reduced per tx (VecStaticBlock.ver_ok_from_u).
        A merged multi-batch overlay needs no special casing: its
        ``updates`` mapping is already newest-wins."""
        pairs = static.u_pairs
        U = len(pairs)
        if not U:
            return static.ver_ok_from_u(
                np.zeros(0, bool), np.zeros((0, 2), np.uint32)
            )
        up, uv = self.state.get_versions_cols(pairs)
        if overlay is not None and overlay.updates:
            idx = getattr(static, "u_index", None)
            if idx is None:  # built on the prefetch thread normally
                idx = static.u_index = dict(zip(pairs, range(U)))
            iget = idx.get
            for pr, vv in overlay.updates.items():
                ui = iget(pr)
                if ui is None:
                    continue
                if vv.value is None:  # in-flight delete
                    up[ui] = False
                else:
                    up[ui] = True
                    uv[ui] = vv.version
        return static.ver_ok_from_u(up, uv)

    def _finish_device(self, pending: "PendingBlock"):
        """Consume the stage-2 packed output: final codes, filter,
        update batch.  Returns None to fall back to the host path
        (consumption-unsafe policy rows)."""
        block, txs = pending.block, pending.txs
        dpre = pending.dpre
        t0 = time.perf_counter()
        out = pending.fetch2()
        t1 = self._t("device_wait", t0)
        # sync-only duration for the guard's deadline: the host-side
        # postprocess below must not count against the DEVICE lane
        self._last_device_sync_s = t1 - t0
        t0 = t1

        # consumption-unsafe rows → exact host interpreter path
        for safe_bits, ents in zip(out["safe"], dpre.group_entries):
            if not np.all(safe_bits[: len(ents)]):
                return None

        # final code assignment, vectorized — same check order as the
        # reference: creator sig → config → policy → mvcc
        sig_valid = out["sig_valid"]
        n_sig = len(sig_valid)
        policy_ok, valid, phantom = out["policy_ok"], out["valid"], out["phantom"]
        nT = len(txs)
        final = np.fromiter((ptx.code for ptx in txs), np.int32, nT)
        und = final == int(C.NOT_VALIDATED)
        cfg = np.fromiter((ptx.is_config for ptx in txs), bool, nT)
        ci_arr = np.fromiter(
            (ptx.creator_item_idx for ptx in txs), np.int64, nT
        )
        svF = np.concatenate([sig_valid, [False]])
        ci_idx = np.where((ci_arr >= 0) & (ci_arr < n_sig), ci_arr, n_sig)
        creator_fail = und & (ci_arr >= 0) & ~svF[ci_idx]
        rp = np.zeros(nT, bool)
        for i in pending.range_phantom:
            rp[i] = True
        sel = np.select(
            [~policy_ok[:nT], rp, valid[:nT], phantom[:nT]],
            [int(C.ENDORSEMENT_POLICY_FAILURE), int(C.PHANTOM_READ_CONFLICT),
             int(C.VALID), int(C.PHANTOM_READ_CONFLICT)],
            default=int(C.MVCC_READ_CONFLICT),
        )
        upd = und & ~cfg & ~creator_fail
        final[upd] = sel[upd]
        final[und & creator_fail] = int(C.BAD_CREATOR_SIGNATURE)
        for i in np.flatnonzero(cfg & und & ~creator_fail).tolist():
            final[i] = self._validate_config(block, txs[i])  # rare
        fl = final.tolist()
        for ptx, c in zip(txs, fl):
            ptx.code = c
        tx_filter = bytes(fl)
        if dpre.rwp is not None:
            batch, history = self._build_updates_flat(
                block.header.number, txs, dpre.rwp, dpre.ns_names,
                dpre.ukeys,
            )
        else:
            batch, history = self._build_updates(block.header.number, txs)
        self._t("postprocess", t0)
        return tx_filter, batch, history

    def _build_updates_flat(self, block_num: int, txs, rwp, ns_names, ukeys):
        """Columnar update batch + history from the native flat write
        arrays — the batch keeps the validator's numpy slabs
        (ColumnarUpdateBatch) so the sqlite backend can apply it with
        one executemany per namespace, and its lazy ``updates`` dict is
        byte-identical (incl. per-tx (ns, key) sort order) to the old
        eager build over parsed rwsets.  Key strings come from the
        already-decoded unique-key table (``ukeys``)."""
        from fabric_tpu.ledger.statedb import ColumnarUpdateBatch

        history = []
        nw = rwp.n_writes  # slice REAL rows; the arrays are capacity-sized
        nk = rwp.n_keys
        w_uid = rwp.w_uid[:nw]
        w_is_del = rwp.w_is_del[:nw]
        vo = rwp.w_val_span[:nw, 0]
        vl = rwp.w_val_span[:nw, 1]
        neg = vo < 0
        if neg.any():  # negative span = empty value, normalize to b""
            vo = np.where(neg, 0, vo)
            vl = np.where(neg, 0, vl)
        ns_of = rwp.ns_of_ukey[:nk].tolist()
        # per-uid apply rank: ONE sort of the unique-key table by
        # (ns, key) replaces the old per-tx row-tuple sorts
        order = sorted(range(nk),
                       key=lambda u: (ns_names[ns_of[u]], ukeys[u]))
        rank = np.empty(nk, np.int64)
        rank[order] = np.arange(nk)
        w_start = rwp.w_start.tolist()
        w_count = rwp.w_count.tolist()
        row_sel = []   # global row indices in final apply order
        txn_chunks = []
        for ptx in txs:
            if ptx.code != C.VALID:
                continue
            i = ptx.idx
            s, c = w_start[i], w_count[i]
            if not c:
                continue
            uids = w_uid[s:s + c]
            ord_ = np.argsort(rank[uids], kind="stable")
            row_sel.append(np.arange(s, s + c)[ord_])
            txn_chunks.append(np.full(c, i, np.int64))
            for uid in uids[ord_].tolist():
                history.append((ns_names[ns_of[uid]], ukeys[uid], i))
        if row_sel:
            rows = np.concatenate(row_sel)
            row_txnum = np.concatenate(txn_chunks)
        else:
            rows = np.zeros(0, np.int64)
            row_txnum = np.zeros(0, np.int64)
        batch = ColumnarUpdateBatch(
            block_num, ns_names, ukeys, ns_of,
            w_uid[rows], w_is_del[rows], vo[rows], vl[rows],
            row_txnum, rwp.blob,
        )
        return batch, history

    def _mvcc_inputs(self, txs, overlay=None):
        mvcc_txs = []
        all_read_keys = set()
        for ptx in txs:
            if ptx.rwset is None or not ptx.undetermined:
                mvcc_txs.append(mvcc_ops.TxRWSet(reads=[], writes=[], range_reads=[]))
                continue
            # re-execute range queries against COMMITTED state: a key
            # committed after simulation but inside the range is a
            # phantom even with no in-block writer (the reference
            # merges committed state into the range re-check,
            # validation/validator.go:205-247, combined_iterator.go:44).
            # Per-result version staleness rides the normal read checks;
            # in-block writers ride the id-interval kernel check.
            if self._committed_range_phantom(ptx, overlay) or (
                overlay is not None and _overlay_range_phantom(ptx, overlay)
            ):
                ptx.code = C.PHANTOM_READ_CONFLICT
                mvcc_txs.append(mvcc_ops.TxRWSet(reads=[], writes=[], range_reads=[]))
                continue
            reads, writes, rqs = ptx.rwset.mvcc_form()
            # metadata-only writes are writers iff they APPLY — the key
            # must exist in committed state (or the in-flight
            # predecessor's batch); a no-op metadata write on an absent
            # key must not conflict later readers (the reference's
            # applyWriteSet leaves the batch untouched there)
            for ns_name, n in ptx.rwset.ns.items():
                for k in n.metadata_writes:
                    if k not in n.writes and self._key_exists(
                        ns_name, k, overlay
                    ):
                        writes.append(("pub", ns_name, k))
            mvcc_txs.append(
                mvcc_ops.TxRWSet(reads=reads, writes=writes, range_reads=rqs)
            )
            all_read_keys.update(k for k, _ in reads)
        return mvcc_txs, self._committed_versions(all_read_keys, overlay=overlay)

    def _key_exists(self, ns: str, key: str, overlay) -> bool:
        if overlay is not None:
            vv = overlay.updates.get((ns, key))
            if vv is not None:
                return vv.value is not None
        return self.state.get_state(ns, key) is not None

    def _committed_versions(self, all_read_keys, overlay=None) -> dict:
        """Bulk-load committed versions for a set of mvcc-form keys
        (the preLoadCommittedVersionOfRSet analog,
        validation/validator.go:27-78).

        ``overlay`` is the predecessor block's UpdateBatch whose ledger
        commit may still be applying concurrently: its entries OVERRIDE
        whatever the racy state read returned — per-key reads are
        atomic and the override is exactly the value the in-flight
        apply will land, so the result equals a serialized read."""
        committed: dict = {}
        if all_read_keys:
            pub_keys = [
                (k[1], k[2]) for k in all_read_keys if k[0] == "pub"
            ]
            vers = self.state.get_versions_bulk(pub_keys)
            for k in all_read_keys:
                if k[0] == "pub" and (k[1], k[2]) in vers:
                    committed[k] = vers[(k[1], k[2])]
                elif k[0] == "pvt":
                    v = self.state.get_version(f"{k[1]}${k[2]}#hashed", _hex(k[3]))
                    if v is not None:
                        committed[k] = v
            if overlay is not None:
                for k in all_read_keys:
                    bk = (
                        (k[1], k[2]) if k[0] == "pub"
                        else (f"{k[1]}${k[2]}#hashed", _hex(k[3]))
                    )
                    vv = overlay.updates.get(bk)
                    if vv is None:
                        continue
                    if vv.value is None:  # delete
                        committed.pop(k, None)
                    else:
                        committed[k] = vv.version
        return committed

    def _committed_range_phantom(self, ptx, overlay=None) -> bool:
        """True iff some committed key falls inside a recorded range
        query but is missing from its recorded results (end_key == ''
        means unbounded, per the reference's open-ended iterators).

        Under pipelining the state walk may still see keys the
        IN-FLIGHT predecessor deleted — those are subtracted via the
        overlay (the insert arm is _overlay_range_phantom)."""
        for ns_name, n in ptx.rwset.ns.items():
            for start, end, results in n.range_queries:
                recorded = {k for k, _ in results}
                for key, _vv in self.state.get_state_range(ns_name, start, end):
                    if key in recorded:
                        continue
                    if overlay is not None:
                        ov = overlay.updates.get((ns_name, key))
                        if ov is not None and ov.value is None:
                            continue  # predecessor deleted it
                    return True
        return False

    def _validate_config(self, block, ptx) -> int:
        """Config-tx validation: structure must parse as a
        ConfigEnvelope and the configured processor must accept it —
        CONFIG envelopes are never rubber-stamped
        (v20/validator.go:397-419)."""
        try:
            env = protoutil.unmarshal(common_pb2.Envelope, block.data.data[ptx.idx])
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            cfg_env = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
        except Exception:
            return C.BAD_PAYLOAD
        if block.header.number == 0:
            # genesis config is the channel's trust anchor — verified
            # out-of-band by the joining admin, not by prior state
            return C.VALID
        if self.config_processor is not None:
            try:
                return self.config_processor.validate_config_tx(ptx, cfg_env)
            except Exception:
                return C.INVALID_OTHER_REASON
        return C.VALID

    def _build_updates(self, block_num: int, txs, overlay=None, sbe=False):
        """Update batch + history for the block's VALID txs.  With
        ``sbe`` (key-level endorsement in play): metadata writes of
        valid txs commit — combined with a value write they ride the
        same put; alone they re-put the existing value with new
        metadata and a version bump (a no-op when the key does not
        exist, the reference's semantics); plain value writes PRESERVE
        the key's existing metadata; deletes clear it."""
        from fabric_tpu.ledger.rwset import encode_metadata

        batch = UpdateBatch()
        history = []

        def _prev(ns, key):
            vv = batch.updates.get((ns, key))
            if vv is not None:
                return vv
            if overlay is not None:
                vv = overlay.updates.get((ns, key))
                if vv is not None:
                    return vv
            return self.state.get_state(ns, key)

        for ptx in txs:
            if ptx.code != C.VALID or ptx.rwset is None:
                continue
            ver = (block_num, ptx.idx)
            for ns_name in sorted(ptx.rwset.ns):
                n = ptx.rwset.ns[ns_name]
                mws = n.metadata_writes if sbe else {}
                for key in sorted(n.writes):
                    val = n.writes[key]
                    if val is None:
                        batch.delete(ns_name, key, ver)
                    elif not sbe:
                        batch.put(ns_name, key, val, ver)
                    else:
                        if key in mws:
                            md = encode_metadata(mws[key])
                        else:
                            prev = _prev(ns_name, key)
                            md = (
                                prev.metadata
                                if prev is not None and prev.value is not None
                                else None
                            )
                        batch.put(ns_name, key, val, ver, metadata=md)
                    history.append((ns_name, key, ptx.idx))
                for key in sorted(mws):
                    if key in n.writes:
                        continue  # combined above
                    prev = _prev(ns_name, key)
                    if prev is None or prev.value is None:
                        continue  # metadata write on absent key: no-op
                    # NO history entry: the reference's history DB
                    # records value writes only (KvRwSet.Writes)
                    batch.put(
                        ns_name, key, prev.value, ver,
                        metadata=encode_metadata(mws[key]),
                    )
                for coll in sorted(n.hashed):
                    hns = f"{ns_name}${coll}#hashed"
                    for kh, (vh, is_del) in sorted(n.hashed[coll].get("writes", {}).items()):
                        if is_del:
                            batch.delete(hns, _hex(kh), ver)
                        else:
                            batch.put(hns, _hex(kh), vh, ver)
        return batch, history


class DefaultValidation(ValidationPlugin):
    """Built-in plugin (analog builtin/default_validation.go +
    v20/validation_logic.go): evaluate one (tx, namespace) pair's
    chaincode policy over the tx's verified endorsements.  Plans are
    compiled once per policy object and cached (the reference caches
    per plugin^channel, plugin_validator.go)."""

    def __init__(self):
        # keyed by the (frozen, hashable) policy AST itself — id()-keys
        # could alias a recycled address after a config update GCs the
        # old policy object
        self._plan_cache: dict[object, pol.BatchPlan] = {}

    def _plan(self, policy) -> pol.BatchPlan:
        plan = self._plan_cache.get(policy)
        if plan is None:
            plan = pol.compile_plan(policy)
            self._plan_cache[policy] = plan
        return plan

    def _match_row(self, plan: pol.BatchPlan, serialized: bytes, ident):
        """Memoized principal-match row for one endorser identity —
        a block re-presents the same few certs thousands of times."""
        cache = getattr(plan, "_row_cache", None)
        if cache is None:
            cache = plan._row_cache = {}
        hit = cache.get(serialized)
        if hit is not None and hit[0] is ident:
            return hit[1]
        # pin the Identity object in the entry: a hit requires the SAME
        # object, so an MSP-cache invalidation (new Identity instances)
        # can never be served a stale principal-match row
        row = np.array([p.matched_by(ident) for p in plan.principals], bool)
        cache[serialized] = (ident, row)
        return row

    def validate_batch_group(self, ctx: BlockValidationCtx, group):
        """ONE vectorized policy reduction per distinct policy over all
        its (tx, namespace) entries — the per-tx closure walk of the
        reference (cauthdsl.go:39) becomes a [T, S, P] count reduction;
        the exact consumption interpreter only runs for the rare rows
        where a signature matches two distinct principals."""
        out = [False] * len(group)
        by_policy: dict[int, list] = {}
        policies: dict[int, object] = {}
        for idx, (ptx, ns) in enumerate(group):
            info = ctx.policy_provider.info(ns)
            key = id(info.policy)
            policies[key] = info.policy
            by_policy.setdefault(key, []).append((idx, ptx))
        for key, entries in by_policy.items():
            policy = policies[key]
            plan = self._plan(policy)
            P = len(plan.principals)
            T = len(entries)
            S = max((len(p.endorsements) for _, p in entries), default=0) or 1
            M = np.zeros((T, S, P), bool)
            for t, (_, ptx) in enumerate(entries):
                for s, (ser, ident) in enumerate(ptx.endorsements):
                    if ctx.sig_valid[ptx.endo_item_idx[s]]:
                        M[t, s] = self._match_row(plan, ser, ident)
            safe = plan.consumption_safe_batch(M)
            ok = plan.evaluate_counts_batch(M)
            for t, (idx, ptx) in enumerate(entries):
                if safe[t]:
                    out[idx] = bool(ok[t])
                else:
                    m = M[t, : len(ptx.endorsements)]
                    out[idx] = bool(pol.evaluate(policy, m))
        return out


def _overlay_range_phantom(ptx, overlay) -> bool:
    """True iff a write of the in-flight predecessor block falls inside
    one of this tx's recorded range queries but is missing from its
    recorded results — the overlay arm of the committed-range
    re-execution (deleted keys ride the per-result read checks)."""
    for ns_name, n in ptx.rwset.ns.items():
        for start, end, results in n.range_queries:
            recorded = {k for k, _ in results}
            for (ns, key), vv in overlay.updates.items():
                if ns != ns_name or vv.value is None:
                    continue
                if key >= start and (not end or key < end) and key not in recorded:
                    return True
    return False


def _sig_item(ident: Identity, message: bytes, der_sig: bytes):
    r, s = sig_to_ints(der_sig)
    qx, qy = ident.public_numbers
    return (int.from_bytes(hashlib.sha256(message).digest(), "big"), r, s, qx, qy)


def _hex(b: bytes) -> str:
    return b.hex()

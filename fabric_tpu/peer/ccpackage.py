"""Chaincode packaging + peer-side install store.

Analog of the reference's lifecycle packaging surface
(internal/peer/lifecycle/chaincode/package.go, install.go,
calculatepackageid.go, getinstalledpackage.go + the
core/chaincode/persistence store): a chaincode package is a tar.gz
with

  metadata.json   {"type": "ccaas", "label": "<label>"}
  code.tar.gz     the code archive; for ccaas it holds connection.json
                  {"address": "host:port"} — the external-builder
                  contract the reference uses for chaincode-as-a-
                  service (no Docker in this runtime, by design)

The package id is ``label:sha256hex(package_bytes)`` — exactly the
reference's PackageID shape, so operator tooling reads familiar ids.
Installed packages persist under the peer's data dir and survive
restarts; the approve step binds an org to a package id, and the
endorser resolves a namespace's ccaas endpoint from the installed
package its org approved (see peer/node.py chaincode resolution).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import re
import tarfile

_LABEL_RE = re.compile(r"^[a-zA-Z0-9][a-zA-Z0-9_.+-]*$")


def _tar_bytes(entries: dict[str, bytes]) -> bytes:
    """Deterministic tar.gz of {name: content} (fixed mtime/owner so
    the same logical package always yields the same package id)."""
    buf = io.BytesIO()
    # mtime pinned in the gzip header AND per-member for determinism
    with tarfile.open(fileobj=buf, mode="w:gz", compresslevel=6,
                      format=tarfile.GNU_FORMAT) as tf:
        for name in sorted(entries):
            data = entries[name]
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            ti.mtime = 0
            ti.uid = ti.gid = 0
            ti.uname = ti.gname = ""
            tf.addfile(ti, io.BytesIO(data))
    raw = bytearray(buf.getvalue())
    raw[4:8] = b"\x00\x00\x00\x00"  # gzip MTIME field
    return bytes(raw)


def _tar_read(raw: bytes) -> dict[str, bytes]:
    out = {}
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r:*") as tf:
        for m in tf.getmembers():
            if not m.isfile() or m.size > 16 * 1024 * 1024:
                continue
            f = tf.extractfile(m)
            if f is not None:
                out[m.name.lstrip("./")] = f.read()
    return out


def package_ccaas(label: str, address: str) -> bytes:
    """Build a ccaas chaincode package (peer lifecycle chaincode
    package --lang ccaas analog)."""
    if not _LABEL_RE.match(label or ""):
        raise ValueError(f"invalid package label {label!r}")
    code = _tar_bytes({
        "connection.json": json.dumps(
            {"address": address}, sort_keys=True
        ).encode(),
    })
    return _tar_bytes({
        "metadata.json": json.dumps(
            {"type": "ccaas", "label": label}, sort_keys=True
        ).encode(),
        "code.tar.gz": code,
    })


def parse_package(raw: bytes) -> dict:
    """→ {"label", "type", "connection": {...}|None}; raises ValueError
    on anything that isn't a well-formed package."""
    try:
        entries = _tar_read(raw)
        meta = json.loads(entries["metadata.json"])
        label = meta["label"]
        cc_type = meta["type"]
    except Exception as e:
        raise ValueError(f"malformed chaincode package: {e}") from None
    if not _LABEL_RE.match(label or ""):
        raise ValueError(f"invalid package label {label!r}")
    conn = None
    if "code.tar.gz" in entries:
        try:
            code = _tar_read(entries["code.tar.gz"])
            if "connection.json" in code:
                conn = json.loads(code["connection.json"])
        except Exception:
            conn = None
    return {"label": label, "type": cc_type, "connection": conn}


def package_id(label: str, raw: bytes) -> str:
    """``label:sha256hex`` (calculatepackageid.go)."""
    return f"{label}:{hashlib.sha256(raw).hexdigest()}"


class PackageStore:
    """Installed-package persistence (core/chaincode/persistence
    Store): packages live as <data_dir>/lifecycle/chaincodes/<id>.tgz
    and survive peer restarts."""

    def __init__(self, data_dir: str):
        self.dir = os.path.join(data_dir, "lifecycle", "chaincodes")
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, pkg_id: str) -> str:
        # filename <label>.<sha256>.tgz (the reference's persistence
        # naming): the hash never contains dots, so rsplit on the last
        # one is unambiguous even for dotted labels
        label, _, digest = pkg_id.rpartition(":")
        if not _LABEL_RE.match(label) or not re.fullmatch(
            r"[0-9a-f]{64}", digest
        ):
            raise ValueError(f"invalid package id {pkg_id!r}")
        return os.path.join(self.dir, f"{label}.{digest}.tgz")

    def install(self, raw: bytes) -> dict:
        """Validate + persist; → {"package_id", "label"}.  Installing
        the same bytes twice is idempotent (the reference returns the
        existing id)."""
        info = parse_package(raw)
        pid = package_id(info["label"], raw)
        path = self._path(pid)
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        return {"package_id": pid, "label": info["label"]}

    def list(self) -> list[dict]:
        """QueryInstalledChaincodes: [{"package_id", "label"}]."""
        out = []
        for name in sorted(os.listdir(self.dir)):
            if not name.endswith(".tgz"):
                continue
            label, _, digest = name[:-4].rpartition(".")
            out.append({
                "package_id": f"{label}:{digest}", "label": label,
            })
        return out

    def get(self, pkg_id: str) -> bytes | None:
        """GetInstalledChaincodePackage: the raw package bytes."""
        try:
            with open(self._path(pkg_id), "rb") as f:
                return f.read()
        except (OSError, ValueError):
            return None

    def connection(self, pkg_id: str) -> dict | None:
        """The ccaas endpoint the package binds (connection.json)."""
        raw = self.get(pkg_id)
        if raw is None:
            return None
        try:
            return parse_package(raw)["connection"]
        except ValueError:
            return None

"""Peer node assembly: ledger + validator + endorser + commit driver
+ client services, as one process.

The analog of internal/peer/node/start.go:190-930 `serve()` compressed
to the components this framework has: a KVLedger per channel, the
TPU-batched BlockValidator on the commit path, the endorser service,
and a deliver-client loop that pulls blocks from the ordering service
and drives StoreBlock (the gossip/privdata coordinator's role,
coordinator.go:151 — gossip dissemination itself is replaced by every
peer pulling from the orderer, which the reference also supports via
useLeaderElection=false + org leaders).

Services exposed over fabric_tpu.comm RPC:
* ``Endorse``      — SignedProposal → ProposalResponse (unary).
* ``DeliverBlocks``— committed-block stream with TRANSACTIONS_FILTER
                     metadata set (client event stream analog).
* ``Query``        — read-only state access (qscc-style convenience).
"""

from __future__ import annotations

import asyncio
import json
import logging

from google.protobuf.message import DecodeError

from fabric_tpu import protoutil
from fabric_tpu.comm.rpc import RpcServer
from fabric_tpu.ledger.kvledger import KVLedger
from fabric_tpu.ledger.statedb import MemVersionedDB
from fabric_tpu.observe import txflow as _txflow
from fabric_tpu.ordering.node import DeliverClient
from fabric_tpu.peer.chaincode import ChaincodeRuntime
from fabric_tpu.peer.endorser import Endorser
from fabric_tpu.peer.validator import BlockValidator, PolicyProvider
from fabric_tpu.protos import common_pb2, proposal_pb2

_log = logging.getLogger("fabric_tpu.peer")


class PeerChannel:
    """One channel's ledger + validator + commit loop on this peer.

    With ``genesis_block`` (the production path — the reference
    requires the channel's genesis/config block at join,
    core/peer/peer.go:235), the channel derives its trust anchor from
    it: channelconfig Bundle → MSPs + policy tree, a lifecycle-backed
    policy provider over the channel's OWN state, and a config-tx
    processor.  The genesis block commits locally WITHOUT validation
    (the admin vouches for it out-of-band) and the deliver loop then
    starts at height 1, so a malicious orderer can never substitute a
    different block 0.  Without a genesis block (dev mode) the caller
    wires msp/provider explicitly and the first delivered block is
    trusted — test-network semantics only."""

    def __init__(self, channel_id: str, data_dir: str, msp_manager=None,
                 policy_provider: PolicyProvider | None = None, state_db=None,
                 config_processor=None, genesis_block=None,
                 snapshot_dir: str | None = None, pipeline_depth: int = 2,
                 verify_chunk: int = 0, mesh_devices: int = 0,
                 mesh_topology=None,
                 coalesce_blocks: int = 0, host_stage_workers: int = 0,
                 recode_device: bool = False,
                 host_stage_mode: str = "thread",
                 trace_ring_blocks: int | None = None,
                 trace_slow_factor: float | None = None,
                 device_fail_threshold: int = 0,
                 device_retries: int = 2,
                 device_recovery_s: float = 30.0,
                 verify_deadline_ms: float = 0.0,
                 state_resident: bool = False,
                 state_resident_mb: int = 64,
                 state_resident_range_bits: int = 12,
                 sidecar_endpoint: str = "",
                 sidecar_weight: float = 1.0,
                 sidecar_recovery_s: float = 5.0,
                 sidecar_ssl=None,
                 async_commit: bool = True,
                 apply_queue_blocks: int = 4):
        self.id = channel_id
        # block-commit span tracer knobs (nodeconfig trace_ring_blocks
        # / trace_slow_factor): configure the process-global tracer the
        # CommitPipeline, validator stage timers, host pool workers and
        # the operations server's /trace endpoint all share
        from fabric_tpu import observe

        observe.configure(ring_blocks=trace_ring_blocks,
                          slow_factor=trace_slow_factor)
        self.tracer = observe.global_tracer()
        # commit-path knobs (nodeconfig pipeline_depth / verify_chunk /
        # coalesce_blocks): depth 2 = CommitPipeline overlap on the
        # deliver loop, N ≥ 3 = deep window (merged multi-batch launch
        # overlays, widened dup-txid window, fsyncs deferred to the
        # blockstore group commit), 1 = strict serial commit_block per
        # block; coalesce_blocks ≥ 2 = multi-block verify-dispatch
        # coalescing over the deliver backlog
        # (CommitPipeline.submit_many)
        self.pipeline_depth = int(pipeline_depth)
        self.coalesce_blocks = int(coalesce_blocks)
        snap_meta = None
        if snapshot_dir is not None:
            from fabric_tpu.ledger.snapshot import create_from_snapshot

            self.ledger, snap_meta = create_from_snapshot(
                snapshot_dir, data_dir, state_db=state_db or MemVersionedDB(),
                async_commit=async_commit,
                apply_queue_blocks=apply_queue_blocks,
            )
        else:
            # async group-commit storage engine (nodeconfig
            # ``async_commit``, default ON): state apply trails the
            # block append on the ledger's applier thread
            self.ledger = KVLedger(data_dir, state_db=state_db or MemVersionedDB(),
                                   async_commit=async_commit,
                                   apply_queue_blocks=apply_queue_blocks)
        config = None
        if genesis_block is not None:
            from fabric_tpu.protos import configtx_pb2

            env = protoutil.unmarshal(
                common_pb2.Envelope, genesis_block.data.data[0]
            )
            payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
            cfg_env = protoutil.unmarshal(configtx_pb2.ConfigEnvelope, payload.data)
            config = cfg_env.config
        elif snap_meta is not None and snap_meta.get("config"):
            from fabric_tpu.protos import configtx_pb2

            config = protoutil.unmarshal(
                configtx_pb2.Config, bytes.fromhex(snap_meta["config"])
            )
        if config is not None:
            from fabric_tpu import channelconfig as chancfg
            from fabric_tpu.peer.lifecycle import LifecyclePolicyProvider

            bundle = chancfg.Bundle(channel_id, config)
            config_processor = config_processor or chancfg.ConfigTxProcessor(bundle)
            self.processor = config_processor
            msp_manager = bundle.msp_manager
            if policy_provider is None:
                policy_provider = LifecyclePolicyProvider(
                    self.ledger.state,
                    ref_resolver=lambda name: (
                        self.processor.bundle.application_policy_ast(name)
                    ),
                )
            if genesis_block is not None and self.ledger.blocks.height == 0:
                from fabric_tpu.ledger.statedb import UpdateBatch

                gb = common_pb2.Block()
                gb.CopyFrom(genesis_block)
                self.ledger.commit_block(
                    gb, bytes([0]), UpdateBatch(), []
                )
            # ACLs over the live bundle (rotates with config updates)
            from fabric_tpu.peer.acl import ACLProvider, PROPOSE

            self.acl = ACLProvider(
                lambda: getattr(self.processor, "bundle", None)
            )
            # the _lifecycle system contract scoped to THIS channel's
            # org set (system-chaincode deploy, start.go:765)
            from fabric_tpu.peer.lifecycle import LIFECYCLE_NS, LifecycleContract

            self.syscc = {
                LIFECYCLE_NS: LifecycleContract(
                    org_lister=lambda: self.processor.bundle.application_orgs()
                )
            }
        else:
            self.processor = config_processor
            self.syscc = {}
            if config_processor is not None and hasattr(config_processor, "bundle"):
                from fabric_tpu.peer.acl import ACLProvider

                self.acl = ACLProvider(
                    lambda: getattr(self.processor, "bundle", None)
                )
            else:
                self.acl = None  # dev mode: no policy source, no ACLs
        if msp_manager is None or policy_provider is None:
            raise ValueError(
                "join without genesis_block/snapshot requires explicit "
                "msp_manager and policy_provider"
            )
        validator_kw = dict(
            block_store=self.ledger.blocks, config_processor=config_processor,
            verify_chunk=verify_chunk, mesh_devices=mesh_devices,
            mesh_topology=mesh_topology,
            host_stage_workers=host_stage_workers,
            recode_device=recode_device, host_stage_mode=host_stage_mode,
            device_fail_threshold=device_fail_threshold,
            device_retries=device_retries,
            device_recovery_s=device_recovery_s,
            verify_deadline_ms=verify_deadline_ms,
            state_resident=state_resident,
            state_resident_mb=state_resident_mb,
            state_resident_range_bits=state_resident_range_bits,
            channel=channel_id,
        )
        if sidecar_endpoint:
            # nodeconfig ``sidecar_endpoint``: the channel's signature
            # batches ship to a shared validation sidecar
            # (fabric_tpu/sidecar) instead of owning a local device
            # lane; sidecar loss latches the local CPU fallback and
            # re-attaches via recovery probes, so the channel stays
            # live through sidecar restarts
            from fabric_tpu.sidecar.validator import SidecarValidator

            self.validator = SidecarValidator(
                msp_manager, policy_provider, self.ledger.state,
                sidecar_endpoint=sidecar_endpoint,
                sidecar_weight=sidecar_weight,
                sidecar_recovery_s=sidecar_recovery_s,
                sidecar_ssl=sidecar_ssl,
                **validator_kw,
            )
        else:
            self.validator = BlockValidator(
                msp_manager, policy_provider, self.ledger.state,
                **validator_kw,
            )
        if snapshot_dir is not None:
            # snapshot join + resident cache (PR 14): warm the device
            # table straight from the snapshot's key ranges instead of
            # faulting the working set in miss-by-miss over the first
            # replayed blocks (ledger/snapshot.py warm_resident; a
            # no-op when the resident knob is off or capacity is hit)
            res = getattr(self.validator, "resident", None)
            if res is not None:
                from fabric_tpu.ledger.snapshot import warm_resident

                warmed = warm_resident(res, snapshot_dir)
                if warmed:
                    _log.info("%s: resident cache warmed with %d keys "
                              "from snapshot", channel_id, warmed)
        from fabric_tpu.peer.coordinator import PvtDataCoordinator
        from fabric_tpu.peer.transient import TransientStore

        self.transient = TransientStore(f"{data_dir}/transient.db")
        self.pvt_puller = None  # async callable injected by the gossip layer

        async def _pull(*a):
            if self.pvt_puller is None:
                return None
            return await self.pvt_puller(*a)

        self.coordinator = PvtDataCoordinator(self.transient, puller=_pull)
        from fabric_tpu.ledger.confighistory import ConfigHistoryDB

        self.confighistory = ConfigHistoryDB(f"{data_dir}/confighistory.db")
        self.transient_retention = 50  # blocks (core.yaml transientstore)
        from fabric_tpu.utils.locks import AsyncRWLock

        # endorsement vs commit: simulations take the SHARED side, the
        # committer the exclusive one (lockbased_txmgr RW semantics,
        # endorser.go:379-401) — endorsements run in parallel with each
        # other and only serialize against block commits
        self.commit_lock = AsyncRWLock()
        self._height_changed = asyncio.Event()
        self._deliver_task: asyncio.Task | None = None
        # the live CommitPipeline while the deliver driver runs — the
        # traffic autopilot actuates runtime knobs through it
        # (apply_knob); None between deliver sessions
        self.pipe = None

    # -- runtime re-knobbing (the traffic autopilot's actuator) ----------

    def apply_knob(self, knob: str, value) -> None:
        """Apply one autopilot knob step to this channel's live commit
        path.  Every setter latches and applies at a block boundary
        (pipeline.set_depth / set_coalesce_blocks, validator.
        set_verify_chunk), so actuation is always mid-stream-safe; a
        channel with no live pipeline just updates the value the next
        deliver session starts from."""
        if knob == "verify_chunk":
            fn = getattr(self.validator, "set_verify_chunk", None)
            if fn is not None:
                fn(int(value))
        elif knob == "host_stage_workers":
            # block-boundary pool resize (validator latch →
            # HostStagePool.set_workers drain-and-rebuild)
            fn = getattr(self.validator, "set_host_stage_workers", None)
            if fn is not None:
                fn(int(value))
        elif knob == "coalesce_blocks":
            # the deliver driver reads this attribute per iteration,
            # so the new group size takes effect on the next drain
            self.coalesce_blocks = int(value)
            if self.pipe is not None:
                self.pipe.set_coalesce_blocks(int(value))
        elif knob == "pipeline_depth":
            # persist so the NEXT deliver session (pipeline rebuilt at
            # reconnect from self.pipeline_depth) keeps the actuation,
            # and a channel with no live pipe doesn't lose it.  The
            # serial/pipelined boundary stays unconditional: a channel
            # configured serial (1) never becomes pipelined at runtime.
            if self.pipeline_depth > 1 and int(value) >= 2:
                self.pipeline_depth = int(value)
            if self.pipe is not None:
                self.pipe.set_depth(int(value))

    @property
    def height(self) -> int:
        return self.ledger.blocks.height

    def collection_config(self, ns: str, coll: str) -> dict | None:
        """Collection config (member orgs, peer counts, BTL) from the
        channel's policy provider — lifecycle-backed when a definition
        is committed, static otherwise; None = undefined."""
        fn = getattr(self.validator.policies, "collection", None)
        return fn(ns, coll) if fn else None

    def make_endorser(self, msp, signer, runtime):
        """Endorser over THIS channel's state, system chaincodes and
        ACLs — the single construction point shared by the Endorse RPC
        and the gateway (endorser.go:304 wiring)."""
        from fabric_tpu.peer.acl import PROPOSE
        from fabric_tpu.peer.chaincode import LayeredRuntime

        acl = getattr(self, "acl", None)
        return Endorser(
            msp, signer, self.ledger.state,
            LayeredRuntime(runtime, getattr(self, "syscc", {})),
            acl_check=(
                (lambda _ch, creator, msg, sig:
                 acl.check(PROPOSE, creator, msg, sig))
                if acl is not None else None
            ),
        )

    async def commit_block(self, block) -> bytes:
        """Validate + commit one block, strictly serially (the
        StoreBlock path).  Direct callers and the ``pipeline_depth=1``
        deliver loop use this; depth-2 streams go through
        ``_run_deliver_pipelined``/CommitPipeline instead, which
        overlaps block n's validation with block n-1's ledger commit.

        The validate call dispatches device kernels (and may compile on
        first use) — it runs in a worker thread so the node's RPC
        services stay responsive (the reference's validator pool,
        v20/validator.go:193)."""
        import time as _time

        loop = asyncio.get_event_loop()

        def _verify_and_validate(b):
            # signature + attestation checks are ECDSA-heavy: keep them
            # off the event loop with the rest of validation
            self.verify_block_signature(b)
            pend = self.validator.validate_launch(b)
            return pend, self.validator.validate_finish(pend)

        async with self.commit_lock.writer():
            t0 = _time.perf_counter()
            pend, (flt, batch, history) = await loop.run_in_executor(
                None, _verify_and_validate, block
            )
            t1 = _time.perf_counter()
            await self._commit_inner(
                block, pend.txs, flt, batch, history, pend.hd_bytes
            )
            # device-resident state (fabric_tpu/state): the serial /
            # anti-entropy path commits OUTSIDE the CommitPipeline, so
            # its write-set delta must reach the resident table here —
            # a bypassed scatter is exactly the stale-version hazard
            # FT015 polices (idempotent if a pipeline ever re-routes)
            rc = getattr(self.validator, "resident_commit", None)
            if rc is not None:
                rc(batch)
            t2 = _time.perf_counter()
        self._commit_metrics(flt, t1 - t0, t2 - t1, t2 - t0)
        self._signal_height()
        return flt

    async def _commit_inner(self, block, txs, flt, batch, history,
                            hd_bytes, root=None, sync=True) -> None:
        """Validated triple → committed ledger state: pvt-data phase,
        ledger commit + fsync, post-commit bookkeeping.  The caller
        holds the commit writer lock; ``txs`` are the block's parsed
        records (under pipelining ``validator.last_parsed`` already
        points at the NEXT launched block, so they ride in
        explicitly).

        ``root``: the block's tracer root span, passed EXPLICITLY —
        this coroutine runs on the event-loop thread, where the
        pipeline committer thread's span attachment cannot follow.

        ``sync=False`` — deep-pipelined commits with more of the
        window in flight behind them (``CommittedBlock.defer_sync``):
        skip the forced per-block fsync and let the blockstore's
        group-commit machinery batch the syncs across the pipeline
        window.  Every barrier/tail/idle-flush commit arrives with
        sync=True and closes the window, so the durability exposure is
        bounded by the ``group_commit`` knob (set it to 1 to fsync
        every add regardless) plus the deliver driver's idle flush; a
        crash inside the window reopens at the last synced boundary
        and replays forward (the PR-6 crash-replay story, re-pinned by
        the windowed-fsync tests)."""
        # pvt phase (StoreBlock, coordinator.go:190-220): cleartext
        # from transient/pull, hash-verified, into pvt namespaces
        from fabric_tpu.peer.transient import encode_kv

        pvt = await self.coordinator.gather(block.header.number, txs, flt)
        for hns, key, value, ver in pvt.updates:
            if value is None:
                batch.delete(hns, key, ver)
            else:
                batch.put(hns, key, value, ver)

        def _expiry(ns, coll):
            # BTL from the collection config: expiringBlk =
            # committingBlk + btl + 1 (pvtdatapolicy.BTLPolicy) —
            # the data stays queryable for btl FULL blocks past its
            # commit, then purge_expired erases store + pvt state
            btl = int((self.collection_config(ns, coll) or {})
                      .get("btl", 0) or 0)
            return block.header.number + btl + 1 if btl > 0 else 0

        pvt_store = {
            (txnum, ns, coll): (encode_kv(kv), _expiry(ns, coll))
            for txnum, colls in pvt.store_data.items()
            for (ns, coll), kv in colls.items()
        }

        # the storage commit runs ON the event-loop thread, as the
        # serial path always did: the transient/pvtdata sqlite stores
        # share single connections with loop-thread gossip handlers
        # (persist/reconcile), so moving this to a worker would
        # interleave transactions on one connection.  The pipeline's
        # overlap is unaffected — the NEXT block validates on the
        # feeder thread while this runs.
        from fabric_tpu import faults as _faults
        from fabric_tpu.observe import global_tracer

        _faults.fire("peer.ledger_commit", block=block.header.number)
        tracer = global_tracer()
        with tracer.span("ledger_commit", parent=root):
            self.ledger.commit_block(
                block, flt, batch, history, pvt_data=pvt_store,
                txids=[(p.txid, p.idx) for p in txs if p.txid],
                hd_bytes=hd_bytes,
            )
        if pvt.missing:
            self.ledger.pvtdata.commit_block(
                block.header.number, {},
                [(txnum, ns, coll, True)
                 for (txnum, _txid, ns, coll) in pvt.missing],
            )
        self.transient.purge_below(
            max(0, block.header.number - self.transient_retention)
        )
        # clients key retries off commit acknowledgment: force any
        # open group-commit fsync window closed BEFORE signalling
        # height / commit status, so an acknowledged block can never
        # be lost to a crash on a quiet channel (the add-block-time
        # lag check only runs while traffic flows).  Deep-pipelined
        # mid-window commits (sync=False) defer this to the window's
        # closing commit — the whole segment file syncs then.
        if sync:
            with tracer.span("fsync", parent=root):
                self.ledger.blocks.sync()
            # tx-flow durable fence (idempotent, first fence wins):
            # on the serial mem-state path this sync is the block's
            # first durability edge; on durable paths the ledger's own
            # fence already stamped and this is a no-op
            _txflow.block_durable(block.header.number)
        self._post_commit(block, flt, batch, txs)

    def _commit_metrics(self, flt: bytes, validate_s: float,
                        commit_s: float, total_s: float) -> None:
        # the reference's commit-path breakdown (kv_ledger.go:712-727)
        from fabric_tpu.ops_metrics import global_registry

        reg = global_registry()
        reg.histogram(
            "ledger_block_processing_time",
            "full StoreBlock wall clock per block (s)",
        ).observe(total_s, channel=self.id)
        reg.histogram(
            "validation_duration", "validate phase per block (s)"
        ).observe(validate_s, channel=self.id)
        reg.histogram(
            "ledger_statedb_commit_time", "storage commit per block (s)"
        ).observe(commit_s, channel=self.id)
        reg.gauge(
            "ledger_blockchain_height", "committed block height"
        ).set(self.height, channel=self.id)
        n_valid = sum(1 for c in flt if c == 0)
        reg.counter(
            "ledger_transaction_count", "committed txs by validity"
        ).add(n_valid, channel=self.id, status="valid")
        reg.counter(
            "ledger_transaction_count", "committed txs by validity"
        ).add(len(flt) - n_valid, channel=self.id, status="invalid")

    def _signal_height(self) -> None:
        self._height_changed.set()
        self._height_changed = asyncio.Event()

    async def _commit_from_pipeline(self, res) -> None:
        """Commit one CommittedBlock on behalf of the pipeline's
        committer thread (the pvt coordinator and the commit lock are
        loop-affine, so the thread bridges here via
        run_coroutine_threadsafe)."""
        import time as _time

        t0 = _time.perf_counter()
        async with self.commit_lock.writer():
            await self._commit_inner(
                res.block, res.pend.txs, res.tx_filter, res.batch,
                res.history, res.pend.hd_bytes, root=res.root_span,
                sync=not getattr(res, "defer_sync", False),
            )
        commit_s = _time.perf_counter() - t0
        # launch + finish ≈ the serial path's validate span, so a
        # depth-1 → depth-2 flip compares like for like (the prefetch
        # parse overlaps the predecessor and is deliberately excluded)
        validate_s = (res.stage_s.get("launch", 0.0)
                      + res.stage_s.get("finish", 0.0))
        self._commit_metrics(res.tx_filter, validate_s, commit_s,
                             validate_s + commit_s)
        self._signal_height()

    def _post_commit(self, block, flt: bytes, batch, txs=None) -> None:
        """Post-commit bookkeeping: lifecycle-cache invalidation when
        the block wrote ``_lifecycle`` (lifecycle.Cache StateListener
        analog) and channel-config bundle rotation for committed CONFIG
        txs (BundleSource update, core/peer/peer.go).

        Uses the block's already-parsed tx records (``txs``; falls back
        to the validator's last parse for legacy callers) — normal
        blocks cost zero extra parsing.  A failure to APPLY a committed
        config is a serious divergence and must be loud, not
        swallowed."""
        pol_provider = self.validator.policies
        if hasattr(pol_provider, "on_block_committed"):
            pol_provider.on_block_committed(batch)
        # record definition changes for point-in-time config queries
        # (confighistory/mgr.go, reconciler eligibility on old blocks)
        from fabric_tpu.peer.lifecycle import LIFECYCLE_NS

        # an upgrade (new committed sequence → possibly a new package/
        # endpoint) must drop lazily-resolved ccaas bindings
        wrote_lifecycle = batch.touches_namespace(LIFECYCLE_NS)
        rt = getattr(self, "runtime", None)
        if rt is not None and wrote_lifecycle:
            rt.invalidate_resolved()

        if wrote_lifecycle:
            prefix = "namespaces/fields/"
            for (ns, key), vv in batch.items():
                if ns == LIFECYCLE_NS and key.startswith(prefix)                         and key.endswith("/Definition") and vv.value:
                    cc_name = key[len(prefix):-len("/Definition")]
                    self.confighistory.record(
                        block.header.number, cc_name, vv.value
                    )
        proc = self.validator.config_processor
        if proc is None or not hasattr(proc, "apply"):
            return
        from fabric_tpu.protos import configtx_pb2, transaction_pb2

        if txs is None:
            txs = getattr(self.validator, "last_parsed", ())
        for ptx in txs:
            if not ptx.is_config or flt[ptx.idx] != transaction_pb2.TxValidationCode.VALID:
                continue
            try:
                env = protoutil.unmarshal(
                    common_pb2.Envelope, block.data.data[ptx.idx]
                )
                payload = protoutil.unmarshal(common_pb2.Payload, env.payload)
                cfg_env = protoutil.unmarshal(
                    configtx_pb2.ConfigEnvelope, payload.data
                )
            except DecodeError:
                continue  # malformed yet VALID can only be genesis noise
            try:
                new_bundle = proc.apply(cfg_env)
                # rotate the validator onto the new membership: stale
                # cached identities (revoked certs, rotated CAs) must
                # not keep validating (reference: bundle update
                # propagation, core/peer/peer.go BundleSource)
                if hasattr(new_bundle, "msp_manager"):
                    self.validator.msp = new_bundle.msp_manager
            except Exception:
                import logging

                logging.getLogger("fabric_tpu.peer").exception(
                    "%s: committed CONFIG tx %d of block %d failed to "
                    "apply — bundle is now STALE relative to the ledger",
                    self.id, ptx.idx, block.header.number,
                )

    def verify_block_signature(self, block) -> None:
        """VerifyBlock at deliver (block_verification.go:243): a block
        arriving from ANY source — deliver stream, anti-entropy pull —
        must carry orderer signatures satisfying the channel's
        /Channel/Orderer/BlockValidation policy before it may commit.
        Without this, one compromised orderer (or an impostor peer) can
        fork peers by serving divergent, individually well-formed
        blocks.  The genesis block is the trust anchor (verified
        out-of-band by the joining admin), and channels whose config
        carries no orderer orgs (dev/test assemblies) have no identity
        set to verify against — both skip."""
        if block.header.number == 0:
            return
        bundle = getattr(self.processor, "bundle", None)
        if bundle is None:
            return
        ordg = bundle.config.channel_group.groups.get("Orderer")
        if ordg is None or not ordg.groups:
            return  # no orderer identity set configured
        from fabric_tpu.channelconfig import SignedData

        signed = [
            SignedData(identity=c, data=d, signature=s)
            for c, d, s in protoutil.block_signed_data(block)
        ]
        if not signed or not bundle.policy_manager.evaluate(
            "/Channel/Orderer/BlockValidation", signed
        ):
            raise ValueError(
                f"block {block.header.number}: orderer block-signature "
                "verification failed (BlockValidation policy not met)"
            )
        self._verify_bft_attestation(block, bundle)

    def _verify_bft_attestation(self, block, bundle) -> None:
        """For BFT channels a single orderer signature is NOT enough —
        one byzantine orderer could sign a forged block.  The block's
        consensus metadata must carry the 2f+1 signed COMMIT messages
        for (view, seq, digest-of-batch), each by a distinct, valid
        orderer-org identity, with the digest recomputed from the
        block's own envelopes and seq strictly increasing along the
        chain (reference: BFT quorum attestations,
        common/deliverclient/block_verification.go:278)."""
        import hashlib
        import json as _json

        from fabric_tpu.protos import orderer_pb2

        ct = bundle.orderer_value("ConsensusType", orderer_pb2.ConsensusType)
        if ct is None or ct.type != "bft":
            return
        meta = orderer_pb2.RaftConfigMetadata()
        meta.ParseFromString(ct.metadata)
        n = len(meta.consenters)
        quorum = 2 * ((n - 1) // 3) + 1 if n else 1

        idx = common_pb2.BlockMetadataIndex.ORDERER
        try:
            omd = _json.loads(bytes(block.metadata.metadata[idx]))
            proof = omd["bft_proof"]
            seq = int(omd["index"])
        except Exception:
            raise ValueError(
                f"block {block.header.number}: missing BFT commit proof"
            )
        payload = _json.dumps(
            [bytes(e).hex() for e in block.data.data]
        ).encode()
        want_digest = hashlib.sha256(payload).hexdigest()

        from fabric_tpu.ordering.bft import COMMIT, _signable

        # votes count only from the CONSENTER SET (identities pinned in
        # the channel config), deduped by identity — not by the
        # unauthenticated "from" label: a single compromised identity
        # cannot fabricate 2f+1 votes by inventing sender names, and no
        # non-consenter identity (app orgs, orderer-org admins/users)
        # can vote at all.  Channels whose config predates consenter
        # identities fall back to orderer-ORG membership.
        consenter_ids = {
            bytes(c.identity) for c in meta.consenters if c.identity
        }
        ordg = bundle.config.channel_group.groups.get("Orderer")
        orderer_orgs = set(ordg.groups) if ordg is not None else set()
        voters = set()  # distinct identity bytes
        for m in proof:
            if not isinstance(m, dict) or m.get("type") != COMMIT:
                continue
            if m.get("digest") != want_digest or int(m.get("seq", -1)) != seq:
                continue
            cert = m.get("from_cert")
            sig = m.get("sig")
            if not cert or not sig:
                continue
            try:
                raw_cert = bytes.fromhex(cert)
                if raw_cert in voters:
                    continue
                if consenter_ids:
                    if raw_cert not in consenter_ids:
                        continue
                ident = bundle.msp_manager.deserialize_identity(raw_cert)
                if not ident.is_valid or ident.msp_id not in orderer_orgs:
                    continue
                if not ident.verify(_signable(m), bytes.fromhex(sig)):
                    continue
            except Exception as e:
                _log.debug("attestation vote rejected: %s", e)
                continue
            voters.add(raw_cert)
        if len(voters) < quorum:
            raise ValueError(
                f"block {block.header.number}: BFT attestation has "
                f"{len(voters)} valid commits, quorum is {quorum}"
            )
        # seq monotonicity along the chain: a replayed proof from an
        # older batch cannot attest a later block
        prev_seq = getattr(self, "_last_bft_seq", None)
        if prev_seq is None and block.header.number >= 2:
            try:
                prev = self.ledger.blocks.get_block(block.header.number - 1)
                prev_seq = int(_json.loads(
                    bytes(prev.metadata.metadata[idx])
                )["index"])
            except Exception:
                prev_seq = None
        if prev_seq is not None and seq <= prev_seq:
            raise ValueError(
                f"block {block.header.number}: BFT proof seq {seq} does "
                f"not advance past predecessor's {prev_seq}"
            )
        self._last_bft_seq = seq

    async def run_deliver(self, orderer_addr: tuple[str, int]):
        """Pull blocks from the orderer starting at our height and
        commit them in order; reconnects forever (deliver client
        failover is caller-side: pass a different address).

        With ``pipeline_depth`` ≥ 2 (the default) blocks stream through
        the CommitPipeline so block n's validation, block n-1's ledger
        commit, and block n+1's parse + device launch overlap; depth 1
        commits strictly serially through ``commit_block``."""
        import contextlib

        dc = DeliverClient(*orderer_addr,
                           ssl_ctx=getattr(self, "client_ssl", None))
        async with contextlib.aclosing(dc.blocks(self.id, start=self.height)) as gen:
            if self.pipeline_depth > 1:
                await self._run_deliver_pipelined(gen)
                return
            async for blk in gen:
                # stream liveness for the censorship monitor: a block
                # ARRIVED (even if its validation is slow) — only a
                # silent stream counts as possible withholding
                self._deliver_progress = (
                    getattr(self, "_deliver_progress", 0) + 1
                )
                if blk.header.number < self.height:
                    continue  # replayed
                await self.commit_block(blk)

    # seconds of stream silence before the in-flight tail is flushed:
    # with depth 2 the newest block stays launched-but-uncommitted
    # until the NEXT submit, and a quiet channel must not leave it
    # dangling (clients block on height for their commit ack) —
    # pipelining engages only while blocks arrive back to back
    PIPELINE_IDLE_FLUSH_S = 0.05

    async def _run_deliver_pipelined(self, gen):
        """Depth-N deliver commit driver over peer.pipeline: the
        production analog of the reference's deliver prefetch +
        committer overlap (gossip/state/state.go:540) — the commit
        path stops paying full launch→finish→commit serialization per
        block.  At depth ≥ 3 up to N−1 predecessors' commits drain
        behind the launch under a merged overlay, with mid-window
        fsyncs deferred to the blockstore's group commit (the idle
        flush below closes the window on a quiet channel)."""
        from fabric_tpu.peer.pipeline import CommitPipeline

        loop = asyncio.get_event_loop()

        def commit_fn(res):
            # committer thread → event loop: the pvt coordinator and
            # commit lock are loop-affine (the loop is free — the
            # deliver task awaits pipeline calls in the executor).
            # Poll with a bounded wait instead of blocking forever: if
            # the loop is torn down before the coroutine runs, the
            # future never resolves and an unbounded .result() would
            # wedge the committer thread — and with it executor
            # shutdown and interpreter exit.
            import concurrent.futures as _cf

            fut = asyncio.run_coroutine_threadsafe(
                self._commit_from_pipeline(res), loop
            )
            while True:
                try:
                    return fut.result(timeout=5.0)
                except _cf.TimeoutError:
                    if fut.done():
                        # completed inside the race window (or the
                        # COMMIT itself raised builtin TimeoutError,
                        # py3.11+): a done future answers non-blocking
                        # with the real value or real error — never
                        # re-raise our own poll timeout as the work's
                        return fut.result(timeout=0)
                    if loop.is_closed():
                        fut.cancel()
                        raise RuntimeError(
                            f"{self.id}: event loop closed while "
                            f"committing block {res.block.header.number}"
                        ) from None

        # orderer block signatures + BFT attestation verify at LAUNCH
        # (caller thread), not at prefetch: a predecessor CONFIG block
        # rotates the orderer set at commit, and the barrier only
        # guarantees that rotation has landed by launch time — a
        # forged block must never launch, and a legitimate block must
        # never be judged by the pre-rotation bundle
        pipe = CommitPipeline(
            self.validator, commit_fn, depth=self.pipeline_depth,
            pre_launch_fn=self.verify_block_signature, channel=self.id,
            coalesce_blocks=self.coalesce_blocks, tracer=self.tracer,
        )
        # expose the live pipe to the autopilot's apply_knob for the
        # duration of this deliver session
        self.pipe = pipe
        # submit() blocks for device syncs and for the committer
        # thread — feeding from the shared default executor could
        # exhaust it when many channels block in submit at once,
        # starving everything else that needs a worker (endorsements,
        # other channels' commits).  A dedicated feeder thread per
        # channel keeps the pools independent.
        from concurrent.futures import ThreadPoolExecutor

        feeder = ThreadPoolExecutor(1, thread_name_prefix="fabtpu-feed")
        # blocks arrive through a reader task + queue so this driver
        # can flush the pipeline's in-flight tail when the stream goes
        # idle (see PIPELINE_IDLE_FLUSH_S) — asyncio.wait_for directly
        # on the generator would cancel its internal stream read
        reader_exc: list = []
        q: asyncio.Queue = asyncio.Queue(maxsize=4)

        async def reader():
            from fabric_tpu import faults as _faults

            try:
                async for blk in gen:
                    # chaos hook: a FaultPlan can cut the stream here
                    # (disconnect/truncate) — the reconnect loop's
                    # backoff + replay-from-height path must absorb it.
                    # afire so a latency fault slows THIS stream via
                    # asyncio.sleep instead of freezing the event loop
                    if _faults.plan() is not None:
                        await _faults.afire("deliver.read",
                                            block=blk.header.number)
                    await q.put(blk)
            except BaseException as e:
                reader_exc.append(e)
            finally:
                await q.put(None)

        rtask = asyncio.ensure_future(reader())
        # height lags the in-flight window, so replay detection tracks
        # the next EXPECTED number, not the committed height
        expect = self.height
        try:
            while True:
                try:
                    if pipe.inflight:
                        blk = await asyncio.wait_for(
                            q.get(), timeout=self.PIPELINE_IDLE_FLUSH_S
                        )
                    else:
                        blk = await q.get()
                except asyncio.TimeoutError:
                    # stream went quiet with a block in flight:
                    # commit the tail now — its clients are waiting
                    await loop.run_in_executor(feeder, pipe.flush)
                    continue
                if blk is None:
                    break  # stream ended (reader_exc carries errors)
                self._deliver_progress = (
                    getattr(self, "_deliver_progress", 0) + 1
                )
                # a concurrent anti-entropy pull may commit past our
                # window — resync to the live height so a redelivered
                # block is skipped (as the serial path does) instead
                # of validated and rejected at the ledger
                expect = max(expect, self.height)
                if blk.header.number < expect:
                    continue  # replayed
                expect = blk.header.number + 1
                if self.pipeline_depth <= 1:
                    # pinned to serial mid-stream (anti-entropy came
                    # up, see gossip.start_anti_entropy): drain the
                    # pipeline, then commit through the locked path
                    await loop.run_in_executor(feeder, pipe.flush)
                    await self.commit_block(blk)
                    continue
                # launch coalescing: opportunistically drain the
                # backlog (no await — only blocks ALREADY queued) so
                # their signature batches ride one device dispatch
                group, stream_end = [blk], False
                while (self.coalesce_blocks >= 2
                       and len(group) < self.coalesce_blocks):
                    try:
                        nxt = q.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if nxt is None:
                        stream_end = True
                        break
                    self._deliver_progress = (
                        getattr(self, "_deliver_progress", 0) + 1
                    )
                    if nxt.header.number < max(expect, self.height):
                        continue  # replayed
                    expect = nxt.header.number + 1
                    group.append(nxt)
                if len(group) == 1:
                    await loop.run_in_executor(feeder, pipe.submit, blk)
                else:
                    await loop.run_in_executor(
                        feeder, pipe.submit_many, group
                    )
                if stream_end:
                    break
            if reader_exc:
                raise reader_exc[0]
        except BaseException:
            # drop the in-flight tail: height never advanced for it,
            # so the reconnect re-delivers from the right place.  A
            # pipeline STAGE exception already failed the pipe closed
            # (quarantining the failing block — pipe.last_failure);
            # say which block so a deterministic poison pill is
            # diagnosable instead of an anonymous reconnect storm.
            if pipe.last_failure is not None:
                num, stage = pipe.last_failure
                _log.warning(
                    "%s: quarantining block %s after a %s-stage "
                    "failure; pipe drained, resuming deliver from "
                    "committed height %d", self.id, num, stage,
                    self.height,
                )
            await loop.run_in_executor(
                feeder, lambda: pipe.close(flush=False)
            )
            raise
        else:
            # stream closed cleanly: flush the verified tail
            await loop.run_in_executor(feeder, pipe.close)
        finally:
            self.pipe = None
            # await the cancelled reader before run_deliver's
            # aclosing() touches the generator: aclose() on a
            # still-running async generator raises and would MASK the
            # real stream/commit error
            rtask.cancel()
            await asyncio.gather(rtask, return_exceptions=True)
            feeder.shutdown(wait=False)

    def start_deliver(self, orderer_addrs: list[tuple[str, int]],
                      censorship_check_s: float = 2.0):
        """Background commit driver with orderer failover AND
        censorship monitoring: an orderer that keeps the Deliver
        stream open while withholding blocks is detected by
        cross-checking the OTHER orderers' reported heights — when the
        stream is silent but the rest of the cluster is ahead of us,
        the connection rotates (the deliver-client BFT stance,
        blocksprovider/bft_censorship_monitor.go + bft_deliverer.go;
        a disconnect-only failover cannot see withholding)."""
        import logging

        self.orderer_addrs = list(orderer_addrs)  # gateway Submit uses these

        log = logging.getLogger("fabric_tpu.peer.deliver")

        async def probe_height(addr) -> int:
            from fabric_tpu.comm.rpc import RpcClient

            cli = RpcClient(*addr, ssl_ctx=getattr(self, "client_ssl", None))
            try:
                await cli.connect()
                res = json.loads(await asyncio.wait_for(
                    cli.unary("Info", json.dumps(
                        {"channel": self.id}).encode()),
                    censorship_check_s,
                ))
                return int(res.get("height", -1)) if res.get(
                    "status") == 200 else -1
            except Exception:
                return -1
            finally:
                try:
                    await cli.close()
                except (OSError, RuntimeError):
                    pass  # orderer already gone

        async def censored(current) -> bool:
            # f+1 corroboration: ONE lying orderer (inflated Info
            # height) must not be able to tear down a healthy stream —
            # the BFT fault budget for the orderer list is
            # f = (N-1)//3, so f+1 distinct claims guarantee an honest
            # voucher
            others = [a for a in orderer_addrs if a != current]
            needed = (len(orderer_addrs) - 1) // 3 + 1
            ahead = 0
            for a in others:
                if await probe_height(a) > self.height:
                    ahead += 1
                    if ahead >= needed:
                        return True
            return False

        async def deliver_monitored(addr):
            t = asyncio.ensure_future(self.run_deliver(addr))
            idle_probes = 0
            try:
                while True:
                    p0 = getattr(self, "_deliver_progress", 0)
                    # quiet channels back the probing off (up to 8x):
                    # the monitor is for WITHHOLDING, not for idling
                    await asyncio.wait(
                        {t},
                        timeout=censorship_check_s * min(8, 1 + idle_probes),
                    )
                    if t.done():
                        return await t  # propagate stream errors
                    if getattr(self, "_deliver_progress", 0) != p0:
                        idle_probes = 0  # blocks are flowing (even if
                        continue         # validation is slow)
                    if len(orderer_addrs) > 1 and await censored(addr):
                        log.warning(
                            "%s: orderer %s serves a silent stream while "
                            "the cluster is ahead of height %d — "
                            "suspecting censorship, rotating",
                            self.id, addr, self.height,
                        )
                        raise RuntimeError("deliver censorship suspected")
                    idle_probes += 1
            finally:
                if not t.done():
                    t.cancel()

        from fabric_tpu.ops_metrics import global_registry
        from fabric_tpu.utils.backoff import Backoff

        reconnects = global_registry().counter(
            "deliver_reconnects_total",
            "deliver stream reconnect attempts by channel",
        )

        async def loop():
            # capped exponential backoff + full jitter (utils.backoff):
            # the old fixed 0.2s retry turned an orderer outage into a
            # lockstep connect storm from every peer; progress (height
            # advanced during the attempt) resets the cadence so a
            # healthy stream that drops reconnects promptly
            bo = Backoff(base=0.2, cap=15.0, jitter=0.5)
            i = 0
            while True:
                addr = orderer_addrs[i % len(orderer_addrs)]
                i += 1
                h0 = self.height
                try:
                    await deliver_monitored(addr)
                except Exception as e:
                    # a deterministic commit failure re-fails forever;
                    # it must at least be VISIBLE
                    if self.height > h0:
                        bo.reset()
                    reconnects.add(1, channel=self.id)
                    delay = bo.next()
                    log.warning(
                        "%s deliver from %s: %s: %s — reconnecting "
                        "from height %d in %.2fs (attempt %d)",
                        self.id, addr, type(e).__name__, e,
                        self.height, delay, bo.attempt,
                    )
                    await asyncio.sleep(delay)

        self._deliver_task = asyncio.ensure_future(loop())

    async def snapshot(self, out_dir: str) -> dict:
        """Export a ledger snapshot at the current height, serialized
        against commits (snapshot_mgmt.go commitStart/commitDone)."""
        from fabric_tpu.ledger.snapshot import generate_snapshot

        cfg = b""
        proc = getattr(self, "processor", None)
        if proc is not None and hasattr(proc, "bundle"):
            cfg = proc.bundle.config.SerializeToString()
        loop = asyncio.get_event_loop()
        async with self.commit_lock.writer():
            # worker thread: a large state export must not freeze the
            # node's RPC services for its duration
            return await loop.run_in_executor(
                None,
                lambda: generate_snapshot(
                    self.ledger, out_dir, channel_id=self.id, config_bytes=cfg
                ),
            )

    async def replay_local(self, src_dir: str,
                           depth: int | None = None) -> dict:
        """Catch this channel up from a LOCAL block store directory
        (``peer ... replay_from`` — a serving peer's copied chain, an
        anti-entropy mirror, or this peer's own pre-wipe store) at
        full pipeline depth with zero inter-block think time
        (peer/replay.py).  Resumes from the committed height — a
        killed replay restarts exactly where it stopped — and holds
        the autopilot in throughput mode for the duration.  Returns
        the replay stats dict."""
        from fabric_tpu.ledger.blockstore import BlockStore
        from fabric_tpu.peer.replay import ReplayCheckpoint, ReplayDriver

        loop = asyncio.get_event_loop()

        def commit_fn(res):
            # committer thread → event loop, exactly the deliver
            # driver's bridge (commit lock + pvt coordinator are
            # loop-affine); bounded poll per the FT009 discipline
            import concurrent.futures as _cf

            fut = asyncio.run_coroutine_threadsafe(
                self._commit_from_pipeline(res), loop
            )
            while True:
                try:
                    return fut.result(timeout=5.0)
                except _cf.TimeoutError:
                    if fut.done():
                        return fut.result(timeout=0)
                    if loop.is_closed():
                        fut.cancel()
                        raise RuntimeError(
                            f"{self.id}: event loop closed while "
                            f"committing replayed block "
                            f"{res.block.header.number}"
                        ) from None

        def hook(pipe):
            self.pipe = pipe

        src = BlockStore(src_dir)
        drv = ReplayDriver(
            self.validator, commit_fn,
            depth=self.pipeline_depth if depth is None else depth,
            checkpoint=ReplayCheckpoint(
                f"{self.ledger.blocks.dir}/replay_checkpoint.json"
            ),
            pre_launch_fn=self.verify_block_signature, channel=self.id,
            coalesce_blocks=self.coalesce_blocks, tracer=self.tracer,
            pipe_hook=hook,
        )
        start = self.height
        from concurrent.futures import ThreadPoolExecutor

        # dedicated feeder thread, like the deliver driver: submit()
        # blocks on device syncs and must not starve the shared pool
        feeder = ThreadPoolExecutor(1, thread_name_prefix="fabtpu-replay")
        try:
            stats = await loop.run_in_executor(
                feeder, lambda: drv.run(src.iter_blocks(start),
                                        start=start)
            )
        finally:
            feeder.shutdown(wait=False)
            src.close()
        stats["resumed_from"] = start
        return stats

    async def wait_height(self, h: int, timeout: float = 30.0):
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        while self.height < h:
            ev = self._height_changed
            remaining = deadline - loop.time()
            if remaining <= 0:
                raise asyncio.TimeoutError(f"height {self.height} < {h}")
            await asyncio.wait_for(ev.wait(), remaining)

    def stop(self):
        if self._deliver_task:
            self._deliver_task.cancel()
        self.validator.close()  # host staging pool worker threads
        self.transient.close()
        self.confighistory.close()
        self.ledger.close()


# single shared default with PeerConfig (nodeconfig is import-light)
from fabric_tpu.nodeconfig import DEFAULT_MAX_PACKAGE_SIZE  # noqa: E402


class PeerNode:
    def __init__(self, node_id: str, data_dir: str, msp_manager, signer,
                 runtime: ChaincodeRuntime | None = None,
                 host: str = "127.0.0.1", port: int = 0, tls=None,
                 max_package_size: int = DEFAULT_MAX_PACKAGE_SIZE,
                 install_require_admin: bool = False,
                 pipeline_depth: int = 2, verify_chunk: int = 0,
                 mesh_devices: int = 0, mesh_topology=None,
                 coalesce_blocks: int = 0,
                 host_stage_workers: int = 0, recode_device: bool = False,
                 host_stage_mode: str = "thread",
                 trace_ring_blocks: int | None = None,
                 trace_slow_factor: float | None = None,
                 slos: str = "",
                 vitals_interval_s: float = 0.0,
                 vitals_retention: int = 240,
                 blackbox_dir: str = "",
                 device_ledger: bool = True,
                 autopilot: bool = False,
                 autopilot_tick_s: float = 1.0,
                 autopilot_knobs: str = "",
                 sign_device: bool = False,
                 sign_batch_max: int = 256,
                 sign_batch_wait_ms: float = 2.0,
                 sign_self_check: bool = False,
                 device_fail_threshold: int = 0,
                 device_retries: int = 2,
                 device_recovery_s: float = 30.0,
                 verify_deadline_ms: float = 0.0,
                 state_resident: bool = False,
                 state_resident_mb: int = 64,
                 state_resident_range_bits: int = 12,
                 faults: str = "",
                 sidecar_endpoint: str = "",
                 sidecar_weight: float = 1.0,
                 sidecar_recovery_s: float = 5.0,
                 sidecar_listen: str = "",
                 sidecar_queue_blocks: int = 8,
                 sidecar_coalesce: int = 4,
                 async_commit: bool = True,
                 apply_queue_blocks: int = 4,
                 tx_flow: bool = True):
        self.id = node_id
        self.dir = data_dir
        self.msp = msp_manager
        self.signer = signer
        self.runtime = runtime or ChaincodeRuntime()
        # commit-path knobs every joined channel inherits (nodeconfig
        # pipeline_depth / verify_chunk / mesh_devices / coalesce_blocks)
        self.pipeline_depth = int(pipeline_depth)
        # async group-commit storage engine (nodeconfig async_commit /
        # apply_queue_blocks, default ON; False = serial fallback)
        self.async_commit = bool(async_commit)
        self.apply_queue_blocks = int(apply_queue_blocks)
        self.verify_chunk = int(verify_chunk)
        self.mesh_devices = int(mesh_devices)
        # declarative mesh topology (parallel.topology.MeshTopology,
        # nodeconfig mesh_shape / mesh_distributed / mesh_coordinator):
        # when configured it wins over the bare mesh_devices count;
        # every joined channel's validator shares the resolved fabric
        self.mesh_topology = mesh_topology
        self.coalesce_blocks = int(coalesce_blocks)
        self.host_stage_workers = int(host_stage_workers)
        self.recode_device = bool(recode_device)
        self.host_stage_mode = host_stage_mode
        # span-tracer knobs (None = leave the global tracer as-is)
        self.trace_ring_blocks = trace_ring_blocks
        self.trace_slow_factor = trace_slow_factor
        # SLO spec (nodeconfig ``slos``): armed at start(), like the
        # tracer knobs — a constructor side effect would let a second
        # node silently wipe the first's engine state
        self.slos = slos
        # flight-data recorder knobs (nodeconfig ``vitals_interval_s``
        # / ``vitals_retention`` / ``blackbox_dir``): armed at start(),
        # like the SLO engine — interval 0 (the default) builds no
        # sampler thread and leaves every incident hook a no-op
        self.vitals_interval_s = float(vitals_interval_s)
        self.vitals_retention = int(vitals_retention)
        self.blackbox_dir = blackbox_dir
        self.vitals = None
        self.blackbox = None
        # device-time launch ledger (nodeconfig ``device_ledger``,
        # default ON): armed refcounted at start() like the recorder —
        # colocated nodes share one ledger, the last release disarms
        self.device_ledger = bool(device_ledger)
        self.launch_ledger = None
        # per-tx flow journal (nodeconfig ``tx_flow``, default ON):
        # armed refcounted at start() like the launch ledger —
        # colocated nodes share one journal, the last release disarms
        self.tx_flow = bool(tx_flow)
        self.txflow_journal = None
        # traffic autopilot (nodeconfig ``autopilot`` / ``autopilot_
        # tick_s`` / ``autopilot_knobs``): built and started at
        # start() — OFF by default, so tier-1/CPU hosts never even
        # construct the controller
        self.autopilot = bool(autopilot)
        self.autopilot_tick_s = float(autopilot_tick_s)
        self.autopilot_knobs = autopilot_knobs
        self.autopilot_ctl = None
        # device-batched ESCC signing (peer/signlane.py): OFF keeps
        # the serial crypto/identity.py signer — batcher + provider
        # are built at start() so a never-started node owns no thread
        self.sign_device = bool(sign_device)
        self.sign_batch_max = int(sign_batch_max)
        self.sign_batch_wait_ms = float(sign_batch_wait_ms)
        self.sign_self_check = bool(sign_self_check)
        self.sign_batcher = None
        self.sign_signer = None
        # device-lane degradation knobs (peer/degrade.py): threshold 0
        # keeps the guard off — the safe default everywhere
        self.device_fail_threshold = int(device_fail_threshold)
        self.device_retries = int(device_retries)
        self.device_recovery_s = float(device_recovery_s)
        self.verify_deadline_ms = float(verify_deadline_ms)
        # device-resident MVCC state knobs (fabric_tpu/state): every
        # joined channel's validator pins an LRU key-range residency
        # cache in device memory.  OFF by default — CPU/tier-1 hosts
        # keep the exact host state_fill path.
        self.state_resident = bool(state_resident)
        self.state_resident_mb = int(state_resident_mb)
        self.state_resident_range_bits = int(state_resident_range_bits)
        # validation sidecar knobs (fabric_tpu/sidecar): endpoint =
        # this peer's channels validate through a remote sidecar;
        # listen = this process ALSO serves one from its device fabric
        self.sidecar_endpoint = sidecar_endpoint
        self.sidecar_weight = float(sidecar_weight)
        self.sidecar_recovery_s = float(sidecar_recovery_s)
        self.sidecar_listen = sidecar_listen
        self.sidecar_queue_blocks = int(sidecar_queue_blocks)
        self.sidecar_coalesce = int(sidecar_coalesce)
        self.sidecar_server = None
        if faults:
            # chaos spec (nodeconfig ``faults`` / FABTPU_FAULTS): arm
            # the process-global fault plan — staging/soak rigs only
            from fabric_tpu import faults as _faults_mod

            _faults_mod.configure(faults)
        # install-surface admission (see _on_install): a size cap
        # always, and optionally an admin-signed request envelope
        self.max_package_size = int(max_package_size)
        self.install_require_admin = bool(install_require_admin)
        from fabric_tpu.peer.ccpackage import PackageStore

        self.packages = PackageStore(data_dir)
        if self.runtime.resolver is None:
            self.runtime.resolver = self._resolve_chaincode
        self.tls = tls  # comm.rpc.TlsProfile: mTLS on every surface
        self.channels: dict[str, PeerChannel] = {}
        self.server = RpcServer(
            host, port, ssl_ctx=tls.server_ctx() if tls else None
        )
        from fabric_tpu.discovery import PeerRegistry

        self.registry = PeerRegistry()  # org → endorsing peers (gateway/discovery)
        # strong refs to fire-and-forget background tasks: the event
        # loop holds tasks weakly, so an unreferenced task can be GC'd
        # mid-flight and its exception is lost
        self._bg: set = set()

    # -- lifecycle install / package resolution ------------------------------

    async def _on_install(self, req: bytes) -> bytes:
        """InstallChaincode: persist a package to the install store
        (internal/peer/lifecycle/chaincode/install.go).

        Admission is layered: the node's mTLS client auth at the
        transport, an unconditional size cap (a connected client must
        not be able to fill the peer's data dir), and — with
        ``install_require_admin`` — a signed request envelope
        ``{"package": hex, "identity": hex, "signature": hex}`` whose
        identity must deserialize to a VALID admin of a known org and
        whose signature must cover the package bytes (the reference's
        install admin-policy check, compressed to one principal)."""
        # the admin envelope hex-encodes the package (2×) and adds
        # identity + signature fields: bound the WIRE request
        # generously before parsing, then cap the DECODED package
        # bytes against the configured max either way
        wire_bound = (
            2 * self.max_package_size + 65536
            if self.install_require_admin else self.max_package_size
        )
        if len(req) > wire_bound:
            return json.dumps({
                "status": 413,
                "message": (
                    f"install request too large: {len(req)} bytes "
                    f"exceeds the bound of {wire_bound}"
                ),
            }).encode()
        raw = req
        if self.install_require_admin:
            err, raw = self._check_install_auth(req)
            if err is not None:
                return err
        if len(raw) > self.max_package_size:
            return json.dumps({
                "status": 413,
                "message": (
                    f"package too large: {len(raw)} bytes exceeds the "
                    f"configured max of {self.max_package_size}"
                ),
            }).encode()
        try:
            info = self.packages.install(raw)
        except ValueError as e:
            return json.dumps({"status": 400, "message": str(e)}).encode()
        return json.dumps({"status": 200, **info}).encode()

    def _check_install_auth(self, req: bytes):
        """→ (error_response | None, package_bytes)."""
        from fabric_tpu.crypto.identity import ROLE_ADMIN

        def deny(msg: str) -> bytes:
            return json.dumps({"status": 403, "message": msg}).encode()

        try:
            envelope = json.loads(req)
            pkg = bytes.fromhex(envelope["package"])
            ident_ser = bytes.fromhex(envelope["identity"])
            sig = bytes.fromhex(envelope["signature"])
        except Exception:
            return deny(
                "install requires an admin-signed request envelope "
                '{"package", "identity", "signature"} (hex fields)'
            ), b""
        try:
            ident = self.msp.deserialize_identity(ident_ser)
        except Exception as e:
            return deny(f"unknown installer identity: {e}"), b""
        if not ident.is_valid:
            return deny("installer identity failed MSP validation"), b""
        my_msp = getattr(self.signer, "msp_id", None)
        if my_msp and ident.msp_id != my_msp:
            # the reference's install policy is LOCAL-MSP admins: an
            # admin of another channel org must not install here
            return deny(
                f"installer org '{ident.msp_id}' is not this peer's "
                f"org '{my_msp}'"
            ), b""
        if getattr(ident, "role", None) != ROLE_ADMIN:
            return deny(
                f"installer '{ident.msp_id}' is not an admin"
            ), b""
        if not ident.verify(pkg, sig):
            return deny("install signature does not cover package"), b""
        return None, pkg

    async def _on_query_installed(self, req: bytes) -> bytes:
        return json.dumps(
            {"status": 200, "installed": self.packages.list()}
        ).encode()

    def _resolve_chaincode(self, name: str, channel: str = ""):
        """Registry-miss launcher: a namespace with a COMMITTED
        lifecycle definition ON THIS CHANNEL whose package (the id
        bound by my org's approval) is installed here gets a ccaas
        proxy to the endpoint its connection.json names — the
        external-builder launch path, minus Docker (by design).  The
        channel scoping matters: the same name on two channels may
        bind different packages."""
        import re as _re

        from fabric_tpu.peer.ccaas import CCaaSProxy
        from fabric_tpu.peer.lifecycle import (
            LIFECYCLE_NS, ChaincodeDefinition, approval_key,
            definition_key,
        )

        ch = self.channels.get(channel)
        if ch is None:
            return None
        my_msp = getattr(self.signer, "msp_id", None)
        state = ch.ledger.state
        vv = state.get_state(LIFECYCLE_NS, definition_key(name))
        if vv is None:
            return None
        try:
            cd = ChaincodeDefinition.from_bytes(vv.value)
        except Exception:
            return None
        # the package THIS ORG approved for the current sequence
        av = state.get_state(
            LIFECYCLE_NS, approval_key(name, cd.sequence, my_msp or "")
        )
        if av is None:
            return None
        try:
            spec = json.loads(av.value)
            pkg_id = spec.get("package_id", "") if isinstance(
                spec, dict) else ""
        except Exception:
            return None
        conn = self.packages.connection(pkg_id) if pkg_id else None
        addr = (conn or {}).get("address", "")
        m = _re.fullmatch(r"(.+):(\d+)", addr)
        if m:
            return CCaaSProxy(name, m.group(1), int(m.group(2)))
        return None

    def join_channel(self, channel_id: str, policy_provider: PolicyProvider | None = None,
                     state_db=None, config_processor=None,
                     genesis_block=None, snapshot_dir=None) -> PeerChannel:
        anchored = genesis_block is not None or snapshot_dir is not None
        ch = PeerChannel(
            channel_id, f"{self.dir}/{channel_id}",
            None if anchored else self.msp,
            policy_provider, state_db, config_processor,
            genesis_block=genesis_block, snapshot_dir=snapshot_dir,
            pipeline_depth=self.pipeline_depth,
            verify_chunk=self.verify_chunk,
            mesh_devices=self.mesh_devices,
            mesh_topology=self.mesh_topology,
            coalesce_blocks=self.coalesce_blocks,
            host_stage_workers=self.host_stage_workers,
            recode_device=self.recode_device,
            host_stage_mode=self.host_stage_mode,
            trace_ring_blocks=self.trace_ring_blocks,
            trace_slow_factor=self.trace_slow_factor,
            device_fail_threshold=self.device_fail_threshold,
            device_retries=self.device_retries,
            device_recovery_s=self.device_recovery_s,
            verify_deadline_ms=self.verify_deadline_ms,
            state_resident=self.state_resident,
            state_resident_mb=self.state_resident_mb,
            state_resident_range_bits=self.state_resident_range_bits,
            sidecar_endpoint=self.sidecar_endpoint,
            sidecar_weight=self.sidecar_weight,
            sidecar_recovery_s=self.sidecar_recovery_s,
            sidecar_ssl=self.tls.client_ctx() if self.tls else None,
            async_commit=self.async_commit,
            apply_queue_blocks=self.apply_queue_blocks,
        )
        ch.client_ssl = self.tls.client_ctx() if self.tls else None
        ch.runtime = self.runtime  # resolved-binding invalidation hook
        self.channels[channel_id] = ch
        gsvc = getattr(self, "gossip_service", None)
        if gsvc is not None:
            ch.pvt_puller = gsvc.pull_pvt_for(channel_id)
        return ch

    # -- services ------------------------------------------------------------

    async def start(self, operations_port: int | None = None):
        self.server.register_unary("Endorse", self._on_endorse)
        self.server.register("DeliverBlocks", self._on_deliver_blocks)
        self.server.register_unary("Query", self._on_query)
        self.server.register_unary("Info", self._on_info)
        self.server.register_unary("Discover", self._on_discover)
        self.server.register_unary("Snapshot", self._on_snapshot)
        self.server.register_unary("InstallChaincode", self._on_install)
        self.server.register_unary("QueryInstalled", self._on_query_installed)
        from fabric_tpu.peer import gateway as gw

        self.gateway = gw.register(self)
        from fabric_tpu.gossip import GossipService

        self.gossip_service = GossipService(self).register()
        await self.server.start()
        self.port = self.server.port
        if self.slos:
            # arm the process-global burn-rate engine on the global
            # tracer's finished-block stream; /slo (operations server
            # below) serves its report.  Spec validity was checked at
            # config load (nodeconfig), so this cannot raise mid-start.
            from fabric_tpu.observe import slo as _slo

            _slo.configure(self.slos)
        if self.sidecar_listen:
            # nodeconfig ``sidecar_listen``: this peer's device fabric
            # ALSO serves a validation sidecar — other peers attach as
            # tenants (the many-peers-one-pod shape without a separate
            # sidecar process)
            from fabric_tpu.sidecar.server import SidecarServer
            from fabric_tpu.sidecar.client import parse_endpoint

            sc_host, sc_port = parse_endpoint(self.sidecar_listen)
            self.sidecar_server = await SidecarServer(
                sc_host, sc_port,
                mesh_devices=self.mesh_devices,
                mesh_topology=self.mesh_topology,
                verify_chunk=self.verify_chunk,
                recode_device=self.recode_device,
                queue_blocks=self.sidecar_queue_blocks,
                coalesce=self.sidecar_coalesce,
                ssl_ctx=self.tls.server_ctx() if self.tls else None,
            ).start()
        if self.sign_device:
            # device-batched ESCC signing: concurrent Endorse/gateway
            # sign requests coalesce into one padded fixed-base device
            # dispatch (ops/p256sign), RFC 6979 nonces — bit-equal to
            # the serial signer the OFF path keeps
            from fabric_tpu.peer import signlane

            try:
                d = signlane.private_scalar(self.signer)
            except ValueError as e:
                _log.warning(
                    "sign_device requested but %s — keeping the "
                    "serial signing path", e,
                )
            else:
                self.sign_batcher = signlane.SignBatcher(
                    signlane.device_sign_backend(
                        d, chunk=self.verify_chunk,
                        mesh_devices=self.mesh_devices,
                        verify_after=self.sign_self_check,
                    ),
                    batch_max=self.sign_batch_max,
                    wait_ms=self.sign_batch_wait_ms,
                ).start()
                self.sign_signer = signlane.BatchedSigner(
                    self.signer, self.sign_batcher
                )
                if self.slos:
                    # endorse-side SLOs: a peer that declares SLOs AND
                    # runs the sign lane arms the default
                    # endorse:latency / endorse_busy:busy pair (unless
                    # the operator's spec already names the endorse
                    # channel) and feeds them from the lane's
                    # per-request wait/BUSY telemetry — the same
                    # values its histograms record — so /slo and
                    # burns() cover the endorsement half of the flow
                    from fabric_tpu.observe import slo as _slo

                    engine = _slo.global_engine()
                    if not any(o.channel == _slo.ENDORSE_CHANNEL
                               for o in engine.objectives):
                        engine.set_objectives(
                            tuple(engine.objectives) + tuple(
                                _slo.parse_slos(
                                    _slo.DEFAULT_ENDORSE_SLOS
                                )
                            )
                        )
                    self.sign_batcher.observer = (
                        _slo.endorse_observer(engine)
                    )
        if self.autopilot:
            # close the adaptive-control loop: the controller reads
            # the global SLO engine + the sidecar scheduler (when this
            # process serves one) + the tracer's flight recorder, and
            # actuates every joined channel's runtime setters.  All
            # knobs stay inside the operator's validated clamp spec.
            from fabric_tpu.control import (
                Autopilot, host_clamped_specs, parse_knob_specs,
                resolve_host_workers_initial, set_global,
            )
            from fabric_tpu.observe.slo import global_engine

            def _apply(knob, value):
                # snapshot: this runs on the controller thread while
                # join_channel mutates the dict on the event loop
                for ch in list(self.channels.values()):
                    ch.apply_knob(knob, value)
                # a colocated sidecar server shares the coalescing
                # pressure signal (its scheduler's queue ages drive
                # the rule), so the cross-tenant dispatch cap follows
                # the same actuation through its drain-boundary setter
                if (knob == "coalesce_blocks"
                        and self.sidecar_server is not None):
                    self.sidecar_server.set_coalesce(int(value))
                # the sign batcher is node-level (one ESCC key, one
                # lane) — actuated here, not per channel
                if (knob == "sign_batch_max"
                        and self.sign_batcher is not None):
                    self.sign_batcher.set_batch_max(int(value))
                if (knob == "sign_batch_wait_ms"
                        and self.sign_batcher is not None):
                    self.sign_batcher.set_wait_ms(float(value))

            def _commit_stats():
                # worst trailing state-apply queue age across this
                # node's channels (same snapshot idiom as _apply:
                # join_channel mutates the dict on the event loop).
                # Serial-commit channels have no engine and contribute
                # nothing — an empty dict reads as signal-absent, so a
                # fully-serial node never fires the apply rule.
                ages = [
                    float(ch.ledger.engine.stats()
                          .get("oldest_age_ms", 0.0))
                    for ch in list(self.channels.values())
                    if getattr(ch.ledger, "engine", None) is not None
                ]
                return {"oldest_age_ms": max(ages)} if ages else {}

            from types import SimpleNamespace

            commit_src = SimpleNamespace(stats=_commit_stats)

            sched = (self.sidecar_server.scheduler
                     if self.sidecar_server is not None else None)
            # the host-workers ladder clamps to this machine's cores
            # (rungs the pool cannot take must not charge cooldowns or
            # log phantom decisions), and its starting value is the
            # RESOLVED pool size, not the raw config (−1 would snap to
            # 0 and invert the knob)
            specs = host_clamped_specs(
                parse_knob_specs(self.autopilot_knobs or None)
            )
            if self.sign_batch_wait_ms == 0:
                # wait_ms=0 is the operator's STATIC flush-immediately
                # choice (the spec parser itself refuses a 0 ladder
                # floor) — snapping it onto the 0.5 rung and stepping
                # "up" on the first empty flush would silently override
                # it, so the knob stays structurally inert here
                specs = {k: v for k, v in specs.items()
                         if k != "sign_batch_wait_ms"}
            self.autopilot_ctl = Autopilot(
                specs, _apply,
                set_weight=(sched.set_weight if sched else None),
                set_shed=(sched.set_shed if sched else None),
                slo=global_engine(), scheduler=sched,
                sign_source=self.sign_batcher,
                commit_source=commit_src,
                tick_s=self.autopilot_tick_s,
                initial={
                    "coalesce_blocks": self.coalesce_blocks,
                    "verify_chunk": self.verify_chunk,
                    "pipeline_depth": self.pipeline_depth,
                    "host_stage_workers": resolve_host_workers_initial(
                        self.host_stage_workers
                    ),
                    "sign_batch_max": self.sign_batch_max,
                    "sign_batch_wait_ms": self.sign_batch_wait_ms,
                },
            )
            if self.sidecar_server is not None:
                self.sidecar_server.autopilot = self.autopilot_ctl
            set_global(self.autopilot_ctl)
            self.autopilot_ctl.start()
        if self.vitals_interval_s > 0 or self.blackbox_dir:
            # flight-data recorder: the sampler keeps trailing metric
            # series (/vitals) and the black-box recorder freezes them
            # — plus trace trees, the autopilot decision log, scheduler
            # stats, SLO burn and fault stats — into one bundle per
            # incident edge.  Armed only here: the default config
            # builds neither the thread nor the recorder.
            from fabric_tpu.observe import blackbox as _blackbox
            from fabric_tpu.observe import timeseries as _timeseries

            # REFCOUNTED arming: colocated nodes share one sampler
            # and one recorder, and only the LAST stop() disarms —
            # neither the creator nor a later arriver stopping first
            # can strand the survivor (acquire/release in the observe
            # modules; a second acquire reuses the live instances)
            if self.vitals_interval_s > 0:
                self.vitals = _timeseries.acquire(
                    interval_s=self.vitals_interval_s,
                    retention=self.vitals_retention,
                )
            def _commit_report():
                # the commit-engine postmortem rows: apply-queue stats
                # plus applied-vs-appended height per async channel —
                # a crash bundle must answer "how far did state apply
                # trail the durable chain" without the process
                out = {}
                for cid, ch in list(self.channels.items()):
                    eng = getattr(ch.ledger, "engine", None)
                    if eng is None:
                        continue
                    st = eng.stats()
                    st["appended_height"] = ch.ledger.height
                    st["synced_height"] = ch.ledger.blocks.synced_height
                    out[cid] = st
                return out or None

            from types import SimpleNamespace as _NS

            self.blackbox = _blackbox.acquire(
                out_dir=self.blackbox_dir,
                scheduler=(self.sidecar_server.scheduler
                           if self.sidecar_server is not None else None),
                commit_source=_NS(report=_commit_report),
            )
        if self.device_ledger:
            # device-time launch ledger: per-launch compile/queue/
            # execute/transfer attribution, /launches, dev:* trace
            # lanes, the autopilot's device_queue_ms signal.  Same
            # refcounted sharing story as the recorder above.
            from fabric_tpu.observe import ledger as _ledgermod

            self.launch_ledger = _ledgermod.acquire()
        if self.tx_flow:
            # per-tx flow journal: endorse→sign→submit→order→durable→
            # apply milestone attribution on one monotonic clock,
            # /txflow, the tx_flow_* histograms and the bench
            # extras.tx_flow payload.  Same refcounted sharing story
            # as the launch ledger.
            from fabric_tpu.observe import txflow as _txflowmod

            self.txflow_journal = _txflowmod.acquire()
            if self.slos:
                # commit-path SLOs: a peer that declares SLOs AND runs
                # the journal arms the default commit_e2e:latency /
                # commit_valid:busy pair (unless the operator's spec
                # already names the commit channel) and feeds them one
                # event per COMPLETED flow — client-visible latency to
                # state visibility, not a per-block proxy
                from fabric_tpu.observe import slo as _slo

                engine = _slo.global_engine()
                if not any(o.channel == _slo.COMMIT_CHANNEL
                           for o in engine.objectives):
                    engine.set_objectives(
                        tuple(engine.objectives) + tuple(
                            _slo.parse_slos(_slo.DEFAULT_COMMIT_SLOS)
                        )
                    )
                self.txflow_journal.slo_feed = _slo.commit_feed(engine)
            if self.sign_batcher is not None:
                # the lane has ONE observer slot — chain the journal's
                # sign_wait stage feed behind whatever the SLO arming
                # installed (both contracts: (wait_ms, busy))
                prev = self.sign_batcher.observer
                txobs = _txflowmod.sign_observer()
                if prev is None:
                    self.sign_batcher.observer = txobs
                else:
                    def _sign_chain(wait_ms, busy, _a=prev, _b=txobs):
                        _a(wait_ms, busy)
                        _b(wait_ms, busy)

                    self.sign_batcher.observer = _sign_chain
        self.operations = None
        if operations_port is not None:
            from fabric_tpu.opsserver import HealthRegistry, OperationsServer

            health = HealthRegistry()
            health.register("rpc_server", lambda: None if self.server._server else "down")

            def _ledgers():  # evaluated per check: covers late joins
                for cid, ch in self.channels.items():
                    if ch.height < 0:
                        return f"ledger {cid} unhealthy"
                return None

            health.register("ledgers", _ledgers)

            def _device_lanes():
                # degraded is a WARNING state the fleet must see, but
                # the channel is still committing (CPU fallback) — so
                # /healthz reports it as a failed check with an
                # explanatory reason rather than silence
                for cid, ch in self.channels.items():
                    g = getattr(ch.validator, "device_guard", None)
                    if g is not None and g.degraded:
                        lane = (
                            "sidecar link"
                            if getattr(ch.validator, "link", None)
                            is not None else "device verify lane"
                        )
                        return (
                            f"channel {cid}: {lane} DEGRADED — "
                            "committing via CPU fallback, recovery "
                            "probe armed"
                        )
                return None

            health.register("device_verify_lane", _device_lanes)
            if self.sidecar_server is not None:
                health.register(
                    "sidecar_server", self.sidecar_server.health_check
                )
            self.operations = await OperationsServer(
                port=operations_port, health=health,
                autopilot=self.autopilot_ctl, vitals=self.vitals,
                blackbox=self.blackbox, launches=self.launch_ledger,
                txflow=self.txflow_journal,
            ).start()
        return self

    @property
    def endorse_signer(self):
        """The ESCC signing provider endorsements flow through: the
        batched device lane when ``sign_device`` armed one, else the
        serial signer — same ``sign``/``serialized`` surface either
        way (peer/signlane.BatchedSigner)."""
        return (self.sign_signer if self.sign_signer is not None
                else self.signer)

    async def stop(self):
        if self.sign_batcher is not None:
            self.sign_batcher.stop()
            self.sign_batcher = None
            self.sign_signer = None
        if self.vitals is not None:
            # refcounted: the shared sampler stops only when the last
            # colocated holder releases (see start())
            from fabric_tpu.observe import timeseries as _timeseries

            _timeseries.release()
            self.vitals = None
        if self.blackbox is not None:
            from fabric_tpu.observe import blackbox as _blackbox

            _blackbox.release()
            self.blackbox = None
        if self.launch_ledger is not None:
            from fabric_tpu.observe import ledger as _ledgermod

            _ledgermod.release()
            self.launch_ledger = None
        if self.txflow_journal is not None:
            from fabric_tpu.observe import txflow as _txflowmod

            _txflowmod.release()
            self.txflow_journal = None
        if self.autopilot_ctl is not None:
            # disable BEFORE stopping so /autopilot (and the gauge)
            # never reads a dead control loop as live, and release the
            # process-global handle if it is ours
            self.autopilot_ctl.set_enabled(False)
            self.autopilot_ctl.stop()
            from fabric_tpu.control import global_autopilot, set_global

            if global_autopilot() is self.autopilot_ctl:
                set_global(None)
        for ch in self.channels.values():
            ch.stop()
        if getattr(self, "gossip_service", None) is not None:
            await self.gossip_service.stop()
        if getattr(self, "operations", None) is not None:
            await self.operations.stop()
        if self.sidecar_server is not None:
            await self.sidecar_server.stop()
        await self.server.stop()

    async def _on_endorse(self, req: bytes) -> bytes:
        signed = proposal_pb2.SignedProposal()
        signed.ParseFromString(req)
        prop = protoutil.unmarshal(proposal_pb2.Proposal, signed.proposal_bytes)
        header = protoutil.unmarshal(common_pb2.Header, prop.header)
        ch_hdr = protoutil.unmarshal(common_pb2.ChannelHeader, header.channel_header)
        chan = self.channels.get(ch_hdr.channel_id)
        if chan is None:
            pr = proposal_pb2.ProposalResponse()
            pr.response.status = 404
            pr.response.message = f"not joined to {ch_hdr.channel_id}"
            return pr.SerializeToString()
        endorser = chan.make_endorser(
            self.msp, self.endorse_signer, self.runtime
        )
        loop = asyncio.get_event_loop()
        async with chan.commit_lock.reader():  # stable height; parallel
            # off the event loop: ECDSA verify + chaincode execution
            # must not stall Deliver/Query/commit service latency
            result = await loop.run_in_executor(
                None, endorser.process_proposal, signed
            )
        if result.pvt_cleartext and result.tx_id:
            # endorsement-time pvt data: transient store + distribution
            # to eligible peers (gossip/privdata/distributor.go)
            chan.transient.persist(result.tx_id, result.pvt_cleartext, chan.height)
            gsvc = getattr(self, "gossip_service", None)
            if gsvc is not None:
                t = asyncio.ensure_future(gsvc.push_pvt(
                    ch_hdr.channel_id, result.tx_id,
                    result.pvt_cleartext, chan.height,
                ))
                self._bg.add(t)
                t.add_done_callback(self._bg.discard)
        return result.response.SerializeToString()

    async def _on_deliver_blocks(self, stream):
        req = json.loads(await stream.__anext__())
        chan = self.channels.get(req["channel"])
        if chan is None:
            await stream.error("no such channel")
            return
        num = req.get("start", 0)
        stop = req.get("stop")
        while stop is None or num <= stop:
            if num < chan.height:
                blk = chan.ledger.blocks.get_block(num)
                if blk is None:
                    # snapshot-pruned range: this peer cannot serve it
                    await stream.error(
                        f"block {num} unavailable (pre-snapshot)"
                    )
                    return
                await stream.send(blk.SerializeToString())
                num += 1
            else:
                # single event loop: no await between the height check
                # and grabbing the event, so no wakeup can be missed
                await chan._height_changed.wait()
        await stream.end()

    async def _on_query(self, req: bytes) -> bytes:
        q = json.loads(req)
        chan = self.channels.get(q["channel"])
        if chan is None:
            return json.dumps({"status": 404}).encode()
        vv = chan.ledger.state.get_state(q["ns"], q["key"])
        return json.dumps({
            "status": 200 if vv is not None else 404,
            # empty bytes is a real committed value, distinct from absent
            "value": vv.value.hex() if vv is not None and vv.value is not None else None,
            "version": list(vv.version) if vv is not None else None,
        }).encode()

    async def _on_info(self, req: bytes) -> bytes:
        q = json.loads(req)
        chan = self.channels.get(q["channel"])
        if chan is None:
            return json.dumps({"status": 404}).encode()
        return json.dumps({"status": 200, "height": chan.height}).encode()

    async def _on_snapshot(self, req: bytes) -> bytes:
        """Admin snapshot request: {channel, out_dir} → signable
        metadata (snapshotgrpc/snapshot_service.go analog)."""
        q = json.loads(req)
        chan = self.channels.get(q["channel"])
        if chan is None:
            return json.dumps({"status": 404}).encode()
        try:
            meta = await chan.snapshot(q["out_dir"])
        except Exception as e:
            return json.dumps({"status": 500, "error": str(e)}).encode()
        return json.dumps({"status": 200, "metadata": meta}).encode()

    async def _on_discover(self, req: bytes) -> bytes:
        """Discovery queries: peers / config / endorsers per channel
        (discovery/service.go analog over the node's registry +
        channel bundles)."""
        from fabric_tpu.discovery import DiscoveryService

        q = json.loads(req)
        channel = q.get("channel", "")

        def bundle_for(ch_id):
            ch = self.channels.get(ch_id)
            proc = getattr(ch, "processor", None) if ch else None
            return getattr(proc, "bundle", None)

        def policy_for(ch_id, cc):
            ch = self.channels.get(ch_id)
            if ch is None:
                return None
            info = ch.validator.policies.info(cc)
            return info.policy if info else None

        svc = DiscoveryService(self.registry, bundle_for, policy_for)
        kind = q.get("query", "peers")
        if kind == "peers":
            return json.dumps({"status": 200, "peers": svc.peers(channel)}).encode()
        if kind == "config":
            cfg = svc.config(channel)
            if cfg is None:
                return json.dumps({"status": 404}).encode()
            return json.dumps({"status": 200, "config": cfg}).encode()
        if kind == "endorsers":
            desc = svc.endorsement_descriptor(channel, q["chaincode"])
            if desc is None:
                return json.dumps({"status": 404}).encode()
            return json.dumps({"status": 200, "descriptor": desc}).encode()
        return json.dumps({"status": 400, "error": f"unknown query {kind}"}).encode()

"""Transaction assembly: proposal → endorsements → envelope.

The client/SDK-side construction path (reference equivalents:
protoutil/txutils.go CreateSignedTx, core/endorser building
ProposalResponse).  Shared by the endorser service, the gateway and
the test/benchmark harnesses.
"""

from __future__ import annotations

import hashlib

from fabric_tpu import protoutil
from fabric_tpu.protos import common_pb2, proposal_pb2, transaction_pb2


def create_signed_proposal(signer, channel_id: str, chaincode: str, args: list[bytes], transient: dict | None = None):
    """→ (SignedProposal, tx_id, proposal) for Evaluate/Endorse."""
    nonce = protoutil.random_nonce()
    creator = signer.serialized
    tx_id = protoutil.compute_tx_id(nonce, creator)
    ext = proposal_pb2.ChaincodeHeaderExtension()
    ext.chaincode_id.name = chaincode
    ch = protoutil.make_channel_header(
        common_pb2.HeaderType.ENDORSER_TRANSACTION,
        channel_id,
        tx_id=tx_id,
        extension=ext.SerializeToString(),
    )
    sh = protoutil.make_signature_header(creator, nonce)
    spec = proposal_pb2.ChaincodeInvocationSpec()
    spec.chaincode_spec.type = proposal_pb2.ChaincodeSpec.EXTERNAL
    spec.chaincode_spec.chaincode_id.name = chaincode
    spec.chaincode_spec.input.args.extend(args)
    cpp = proposal_pb2.ChaincodeProposalPayload(input=spec.SerializeToString())
    for k, v in (transient or {}).items():
        cpp.TransientMap[k] = v
    prop = proposal_pb2.Proposal(
        header=common_pb2.Header(
            channel_header=ch.SerializeToString(),
            signature_header=sh.SerializeToString(),
        ).SerializeToString(),
        payload=cpp.SerializeToString(),
    )
    pbytes = prop.SerializeToString()
    signed = proposal_pb2.SignedProposal(
        proposal_bytes=pbytes, signature=signer.sign(pbytes)
    )
    return signed, tx_id, prop


def proposal_hash(prop: proposal_pb2.Proposal) -> bytes:
    return hashlib.sha256(prop.SerializeToString()).digest()


def create_proposal_response(
    prop: proposal_pb2.Proposal,
    rwset_bytes: bytes,
    endorser_signer,
    chaincode: str,
    response_payload: bytes = b"",
    events: bytes = b"",
    status: int = 200,
) -> proposal_pb2.ProposalResponse:
    """Endorse: build prp, sign prp‖endorser (the exact bytes the TPU
    kernel verifies at commit — validator_keylevel.go:244-260)."""
    cca = proposal_pb2.ChaincodeAction(results=rwset_bytes, events=events)
    cca.response.status = status
    cca.response.payload = response_payload
    cca.chaincode_id.name = chaincode
    prp = proposal_pb2.ProposalResponsePayload(
        proposal_hash=proposal_hash(prop), extension=cca.SerializeToString()
    )
    prp_bytes = prp.SerializeToString()
    endorser = endorser_signer.serialized
    resp = proposal_pb2.ProposalResponse(payload=prp_bytes)
    resp.response.status = status
    resp.endorsement.endorser = endorser
    resp.endorsement.signature = endorser_signer.sign(prp_bytes + endorser)
    return resp


def prepare_transaction(
    prop: proposal_pb2.Proposal,
    responses: list[proposal_pb2.ProposalResponse],
) -> common_pb2.Payload:
    """Unsigned tx payload from matching proposal responses — what the
    gateway's Endorse returns for the CLIENT to sign (the gateway never
    holds client keys; gateway/endorse.go prepared-transaction flow)."""
    if not responses:
        raise ValueError("no proposal responses")
    payloads = {r.payload for r in responses}
    if len(payloads) != 1:
        raise ValueError("proposal responses disagree")
    header = common_pb2.Header()
    header.ParseFromString(prop.header)
    cap = transaction_pb2.ChaincodeActionPayload(
        chaincode_proposal_payload=prop.payload
    )
    cap.action.proposal_response_payload = responses[0].payload
    for r in responses:
        cap.action.endorsements.add(
            endorser=r.endorsement.endorser, signature=r.endorsement.signature
        )
    tx = transaction_pb2.Transaction()
    tx.actions.add(header=header.signature_header, payload=cap.SerializeToString())
    return common_pb2.Payload(header=header, data=tx.SerializeToString())


def assemble_transaction(
    prop: proposal_pb2.Proposal,
    responses: list[proposal_pb2.ProposalResponse],
    creator_signer,
) -> common_pb2.Envelope:
    """Signed tx envelope from matching proposal responses
    (protoutil CreateSignedTx semantics: all payloads must agree)."""
    payload = prepare_transaction(prop, responses)
    return protoutil.sign_envelope(payload, creator_signer)

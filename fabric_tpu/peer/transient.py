"""Transient store: endorsement-time private data held until commit.

Reference: core/transientstore/store.go — the peer stores each
endorsement's private write-set cleartext keyed by txid, purges entries
below a retention height, and the commit-time coordinator reads it
back (gossip/privdata/coordinator.go:190).  Distribution to other
eligible peers writes into THEIR transient stores (PvtPush)."""

from __future__ import annotations

import json
import sqlite3


# canonical pvt cleartext encoding lives with the store; re-exported
# here for the peer-layer callers
from fabric_tpu.ledger.pvtdata import decode_kv, encode_kv  # noqa: F401


class TransientStore:
    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS pvt ("
            " txid TEXT, ns TEXT, coll TEXT, key TEXT, value BLOB,"
            " received_at_block INTEGER,"
            " PRIMARY KEY (txid, ns, coll, key))"
        )
        self._conn.commit()

    def persist(self, txid: str, cleartext: dict, height: int) -> None:
        """cleartext: {(ns, coll): {key: value|None}} — the simulator's
        pvt output (simulator.done())."""
        rows = []
        for (ns, coll), kv in cleartext.items():
            for key, value in kv.items():
                rows.append((txid, ns, coll, key, value, height))
        if rows:
            self._conn.executemany(
                "INSERT OR REPLACE INTO pvt VALUES (?,?,?,?,?,?)", rows
            )
            self._conn.commit()

    def get(self, txid: str) -> dict:
        """→ {(ns, coll): {key: value}} for one txid."""
        out: dict = {}
        for ns, coll, key, value in self._conn.execute(
            "SELECT ns, coll, key, value FROM pvt WHERE txid=?", (txid,)
        ):
            out.setdefault((ns, coll), {})[key] = value
        return out

    def purge_below(self, height: int) -> int:
        cur = self._conn.execute(
            "DELETE FROM pvt WHERE received_at_block < ?", (height,)
        )
        self._conn.commit()
        return cur.rowcount

    def close(self):
        self._conn.close()

"""Transaction simulator: builds read/write sets by executing
chaincode against committed state.

Analog of the reference's lock-based TxSimulator
(core/ledger/kvledger/txmgmt/txmgr/tx_simulator.go): reads record the
committed version (block, txnum); writes are buffered, never applied;
range scans record their result versions AND the scan bounds so the
commit-time phantom re-check can re-execute them
(rwsetutil rangequery capture).  Private-data writes go to the hashed
collection space (sha256 key/value hashes on the public rwset) with
the cleartext kept aside for the transient store.

Simulation runs against a snapshot-height view: the ledger-wide commit
lock (endorser.go:379-401) is an asyncio lock owned by the peer node;
this object just records."""

from __future__ import annotations

import hashlib

from fabric_tpu.ledger.rwset import TxRWSet


class TxSimulator:
    def __init__(self, state_db):
        self.state = state_db
        self.rwset = TxRWSet()
        self.pvt_cleartext: dict = {}  # (ns, coll) -> {key: value|None}
        self._done = False

    # -- public state -------------------------------------------------------

    def get_state(self, ns: str, key: str) -> bytes | None:
        vv = self.state.get_state(ns, key)
        n = self.rwset.ns_rwset(ns)
        if key not in n.writes:  # read-your-own-writes doesn't re-read
            n.reads.setdefault(key, vv.version if vv is not None else None)
        if key in n.writes:
            return n.writes[key]
        return vv.value if vv is not None else None

    def set_state(self, ns: str, key: str, value: bytes) -> None:
        self.rwset.ns_rwset(ns).writes[key] = value

    def delete_state(self, ns: str, key: str) -> None:
        self.rwset.ns_rwset(ns).writes[key] = None

    def get_state_range(self, ns: str, start: str, end: str, limit: int = 0):
        """Iterate committed [start, end); records results + bounds for
        the phantom re-check.  end == '' scans to the namespace end."""
        n = self.rwset.ns_rwset(ns)
        results = []
        out = []
        for key, vv in self.state.get_state_range(ns, start, end, limit):
            results.append((key, vv.version))
            out.append((key, vv.value))
        n.range_queries.append((start, end, results))
        return out

    def set_state_metadata(self, ns: str, key: str, metadata: dict) -> None:
        self.rwset.ns_rwset(ns).metadata_writes[key] = dict(metadata)

    def set_state_validation_parameter(self, ns: str, key: str,
                                       policy_bytes: bytes) -> None:
        """Shim SetStateValidationParameter: a metadata write whose
        VALIDATION_PARAMETER entry is a serialized
        SignaturePolicyEnvelope — the key-level endorsement policy the
        commit-path SBE pass enforces (statebased/validator_keylevel)."""
        from fabric_tpu.ledger.rwset import VALIDATION_PARAMETER

        self.set_state_metadata(ns, key, {VALIDATION_PARAMETER: policy_bytes})

    def get_state_validation_parameter(self, ns: str, key: str) -> bytes | None:
        """Committed key-level policy (metadata reads are not recorded
        in the read set — the reference's GetStateMetadata likewise
        rides outside MVCC)."""
        from fabric_tpu.ledger.rwset import (
            VALIDATION_PARAMETER, decode_metadata,
        )

        vv = self.state.get_state(ns, key)
        if vv is None or not vv.metadata:
            return None
        return decode_metadata(vv.metadata).get(VALIDATION_PARAMETER)

    # -- private data (collections) ----------------------------------------

    def get_private_data(self, ns: str, coll: str, key: str) -> bytes | None:
        kh = hashlib.sha256(key.encode()).digest()
        hns = f"{ns}${coll}#hashed"
        vv = self.state.get_state(hns, kh.hex())
        coll_rw = self.rwset.ns_rwset(ns).hashed.setdefault(
            coll, {"reads": {}, "writes": {}}
        )
        coll_rw["reads"].setdefault(kh, vv.version if vv is not None else None)
        clear = self.pvt_cleartext.get((ns, coll), {})
        if key in clear:
            return clear[key]
        return None  # cleartext lives off-ledger; only the hash is public

    def set_private_data(self, ns: str, coll: str, key: str, value: bytes) -> None:
        kh = hashlib.sha256(key.encode()).digest()
        vh = hashlib.sha256(value).digest()
        coll_rw = self.rwset.ns_rwset(ns).hashed.setdefault(
            coll, {"reads": {}, "writes": {}}
        )
        coll_rw["writes"][kh] = (vh, False)
        self.pvt_cleartext.setdefault((ns, coll), {})[key] = value

    # -- results -------------------------------------------------------------

    def done(self) -> tuple[bytes, dict]:
        """→ (serialized public rwset for ChaincodeAction.results,
        private cleartext for the transient store)."""
        self._done = True
        return self.rwset.to_proto().SerializeToString(), self.pvt_cleartext

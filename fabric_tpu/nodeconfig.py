"""Typed node configuration with defaults, validation, and env-var
overrides.

Analog of the reference's viper-backed core.yaml / orderer.yaml
(core/peer/config.go, orderer/common/localconfig/config.go,
common/viperutil): operators get a SCHEMA — unknown keys are errors
that name the key (with a did-you-mean), type mismatches are errors
that name the key and both types, and every scalar knob can be
overridden without editing files via ``FABTPU_<KEY>`` environment
variables (``FABTPU_PORT=7051``, ``FABTPU_TLS_CA=/path``,
``FABTPU_WAL_RETENTION=512`` — the ``CORE_``/``ORDERER_`` prefix
convention, unified).

The on-disk format stays JSON (what the CLI already reads); this
module is the typing/validation layer over it.
"""

from __future__ import annotations

import dataclasses
import difflib
import json
import os
from dataclasses import dataclass, field


class ConfigError(ValueError):
    """A configuration problem, phrased so the operator can fix it."""


#: install-store admission cap (peer/node.py _on_install; the
#: reference's MaxRecvMsgSize is 100MB — ccaas packages are a few KB
#: of tar, 16MB is generous).  Defined here so PeerConfig and direct
#: PeerNode constructions share ONE default.
DEFAULT_MAX_PACKAGE_SIZE = 16 * 1024 * 1024


# -- leaf sections ----------------------------------------------------------


@dataclass
class TlsConfig:
    """Node mTLS material (cryptogen's nodes/<name>/tls layout)."""

    cert: str = ""
    key: str = ""
    ca: str = ""


@dataclass
class ChannelRef:
    name: str = ""
    genesis: str = ""            # path to the genesis block
    snapshot_dir: str = ""       # join-from-snapshot directory
    # local catch-up replay source (peer/replay.py): a block-store
    # directory holding the chain (a serving peer's copied store, an
    # anti-entropy mirror, this peer's own pre-wipe store).  On start
    # the channel replays it at full pipeline depth — resuming from
    # the committed height — BEFORE the deliver loop attaches.
    # Composes with snapshot_dir: snapshot bootstraps state at H,
    # replay validates H+1.. from the store.
    replay_from: str = ""
    orderers: list = field(default_factory=list)  # [[host, port], ...]
    anti_entropy: bool = False   # background gossip catch-up pulls


@dataclass
class ChaincodeRef:
    """Statically registered ccaas endpoint (the lifecycle install
    flow resolves chaincodes dynamically; this is the operator
    shortcut)."""

    name: str = ""
    host: str = "127.0.0.1"
    port: int = 0


@dataclass
class PeerRef:
    msp_id: str = ""
    host: str = "127.0.0.1"
    port: int = 0


# -- node configs -----------------------------------------------------------


@dataclass
class PeerConfig:
    """The peer's knob surface (core/peer/config.go analog)."""

    id: str = ""
    data_dir: str = ""
    msp_id: str = ""
    msp_dir: str = ""
    host: str = "127.0.0.1"
    port: int = 0
    operations_port: int | None = None
    org_msps: list = field(default_factory=list)      # org MSP dirs
    chaincodes: list = field(default_factory=list)    # [ChaincodeRef]
    peers: list = field(default_factory=list)         # [PeerRef]
    channels: list = field(default_factory=list)      # [ChannelRef]
    tls: TlsConfig | None = None
    # ledger/commit knobs
    group_commit: int = 8            # blockstore fsync window (blocks)
    # async group-commit storage engine (ledger/committer.py): block
    # append stays synchronous (the durability boundary), state-DB
    # apply trails on a dedicated applier thread behind a pending-batch
    # read overlay — verdicts stay bit-equal to the serial engine.
    # False = serial fallback (state applied before commit_block
    # returns, the pre-PR-17 critical path).
    async_commit: bool = True
    # apply-queue bound in BLOCKS: commit_block backpressures at the
    # block boundary once this many batches trail, so apply lag (and
    # crash-recovery replay) stays bounded
    apply_queue_blocks: int = 4
    transient_retention: int = 100   # transient-store purge horizon
    deliver_censorship_check_s: float = 2.0
    # commit pipeline (peer/pipeline.py CommitPipeline): depth 2 =
    # deliver prefetch + committer-thread overlap with the predecessor
    # batch as a launch overlay; N >= 3 = deep window (block n on
    # device while n-1 commits and n-2 fsyncs — up to N-1 in-flight
    # predecessors, their batches MERGED into the launch overlay, the
    # dup-txid window widened to all of them, and mid-window fsyncs
    # deferred to the blockstore's group commit); 1 = strict serial
    # launch→finish→commit per block (the correctness oracle).  Depth
    # 3+ needs a real accelerator to win — the default stays 2 so
    # CPU-only hosts keep the exact classic path.
    pipeline_depth: int = 2
    # signature-verify microbatch: signatures per device chunk with
    # double-buffered dispatch (ops/p256v3.py); 0 = one monolithic
    # launch per block
    verify_chunk: int = 0
    # device-mesh sharding of the production dispatch (parallel/mesh):
    # verify batches and the fused stage-2 lanes shard axis 0 over the
    # first N local devices; -1 = all local devices (the multi-chip
    # default: sharding engages whenever n_devices > 1), 0 = off.
    # A 1-device resolution is a no-op, so CPU-only hosts pay nothing.
    mesh_devices: int = 0
    # declarative mesh topology (parallel/topology.py): "" = off (the
    # bare mesh_devices count above rules), "8" = 1-D data mesh over 8
    # devices, "2x4" = data x replica grid.  When the shape doesn't fit
    # the visible device count the node degrades to the local auto mesh
    # with a warning rather than refusing to start.
    mesh_shape: str = ""
    # span the mesh across jax.distributed processes (pod slices):
    # every participating process runs the same config with its own
    # mesh_process_id; requires mesh_coordinator on all of them.  A
    # failed coordinator handshake degrades to the local mesh.
    mesh_distributed: bool = False
    # coordinator "host:port" for jax.distributed.initialize (process 0
    # listens there); required when mesh_distributed is on
    mesh_coordinator: str = ""
    # this process's rank in the distributed mesh, in [0, n_processes)
    mesh_process_id: int = 0
    # total process count in the distributed mesh
    mesh_num_processes: int = 1
    # multi-block launch coalescing (CommitPipeline.submit_many): when
    # the deliver backlog holds ≥ 2 blocks, concatenate up to N blocks'
    # signature batches into one padded verify dispatch.  0/1 = off.
    # Like verify_chunk, wins need a real accelerator.
    coalesce_blocks: int = 0
    # host staging pool (parallel/hostpool.py): shard the per-block
    # HOST pipeline — envelope parse fan-out, per-signature admission +
    # Montgomery batch inversion + residue dgemm, device-path
    # preprocessing — across N worker threads per validator.  0 = off
    # (serial staging), -1 = one worker per core, n = n workers.
    # Bit-equal to serial staging; enable on multi-core hosts whose
    # sharded device outruns its single-threaded feeder.
    host_stage_workers: int = 0
    # host staging pool flavor: "thread" (default — the staging hot
    # loops are numpy/hashlib/native-C and release the GIL) or
    # "process" for Python-bound CUSTOM staging workloads on a
    # directly-constructed HostStagePool.  The validator's built-in
    # staging is shared-memory (in-place slab writes) and always runs
    # on threads — it coerces "process" back with a warning.
    host_stage_mode: str = "thread"
    # window recoding location (ops/p256v3.py): ship u1/u2 as 16-bit
    # scalar limbs and derive the 4-bit window digits ON DEVICE, so
    # the packed verify H2D frame shrinks (window planes 4×, whole
    # frame ~1.4×).  Default False = host recoding (the native
    # ec_prepare path computes windows for free; CPU-only hosts have
    # no H2D frame worth shrinking).  Bit-equal either way.
    recode_device: bool = False
    # block-commit span tracer (fabric_tpu/observe): flight-recorder
    # ring holding the span trees of the last N committed blocks,
    # served at /trace on the operations server and exportable as
    # Chrome trace JSON (Perfetto).  Always-on and cheap (perf_counter
    # pairs + one ring append per block); 0 disables tracing entirely
    # (overhead measurement / paranoia).
    trace_ring_blocks: int = 32
    # slow-block watchdog: WARN with the full span breakdown when a
    # block's submit→commit time exceeds this multiple of the trailing
    # median (armed after 8 committed blocks); 0 disables the watchdog
    # while keeping the flight recorder.
    trace_slow_factor: float = 5.0
    # declarative latency/error SLOs (fabric_tpu/observe/slo.py):
    # faults-style spec string, e.g.
    # 'commit:latency:ms=250;busy:busy:pct=5' — per-channel rolling
    # burn rates over the tracer's finished-block stream, served at
    # /slo on the operations server with slo_burn_rate{slo,window,
    # channel} gauges and a fast-burn WARN.  Empty = no objectives.
    # The engine rides the tracer, so trace_ring_blocks=0 silences
    # SLOs too.  FABTPU_SLOS overrides like any scalar.
    slos: str = ""
    # flight-data recorder (fabric_tpu/observe/timeseries.py +
    # blackbox.py): with vitals_interval_s > 0, a daemon sampler walks
    # the metrics registry every interval and keeps per-metric bounded
    # rings of (t, value) points — delta-aware for counters and
    # histograms — served at /vitals on the operations server and
    # frozen into black-box incident bundles when an incident edge
    # fires (degrade latch, autopilot shed, SLO fast burn, pipeline
    # fail-closed, injected crash).  0 = recorder OFF (the default):
    # no sampler thread exists and every incident hook is one global
    # read.  vitals_retention bounds each series ring.
    vitals_interval_s: float = 0.0
    vitals_retention: int = 240
    # black-box bundle directory: each incident writes one bounded
    # JSON bundle here (blackbox-<seq>-<kind>.json) in addition to the
    # in-memory index /vitals serves.  "" keeps bundles in memory only
    # (still served at /vitals?incident=K while the recorder is
    # armed).  Setting blackbox_dir WITHOUT vitals_interval_s arms the
    # incident recorder alone — bundles then carry trace/SLO/autopilot
    # context but no metric trails.
    blackbox_dir: str = ""
    # device-time launch ledger (fabric_tpu/observe/ledger.py): wraps
    # every device dispatch (stage-2 verify/MVCC, the sign-kernel
    # flush, resident-table scatters, sidecar batches) and decomposes
    # device_wait into compile / queue / execute / transfer per
    # launch, with program-cache hit rates and per-owner HBM
    # watermarks — served at /launches, mirrored as dev:* child spans
    # in /trace, and read by the autopilot's device_queue_ms signal.
    # Default ON: an armed ledger is a few perf_counter reads per
    # launch (no thread); OFF makes every dispatch hook one global
    # read + None check and registers no instruments.
    device_ledger: bool = True
    # per-transaction flow journal (fabric_tpu/observe/txflow.py):
    # endorse → sign flush → submit → order → durable append → state
    # visibility milestones on one monotonic clock, keyed by tx_id —
    # served at /txflow, recorded as tx_flow_* histograms with trace
    # exemplars, frozen into blackbox bundles, and (with ``slos``)
    # feeding the default commit_e2e / commit_valid objectives one
    # event per completed flow.  Default ON: an armed journal is a
    # few perf_counter reads + one small dict per tx; OFF makes every
    # milestone hook one global read + None check and registers no
    # instruments.
    tx_flow: bool = True
    # device-lane degradation (peer/degrade.py DeviceLaneGuard): after
    # device_fail_threshold CONSECUTIVE device-verify failures the
    # validator latches a degraded CPU mode (ops/p256.verify_host +
    # the host MVCC path — correctness identical, the channel stays
    # live) with a recovery probe every device_recovery_s.  0 = guard
    # off entirely (failures raise through, today's behavior) — the
    # safe default for CPU-only hosts and tier-1.
    device_fail_threshold: int = 0
    # device-launch attempts retried (capped exponential backoff +
    # jitter) before a block falls back to the CPU lane; counts on
    # device_verify_retries_total.  Only meaningful with the guard on.
    device_retries: int = 2
    # seconds between recovery probes while degraded: one block rides
    # the device lane; success re-arms it (validator_degraded gauge 0)
    device_recovery_s: float = 30.0
    # device verify deadline (ms): a device launch/sync slower than
    # this COUNTS AS A FAILURE toward the degraded latch.  The result
    # is still used — a blocked XLA sync cannot be preempted from
    # Python — so this is a latch signal for future blocks, not a
    # per-block abort.  0 = no deadline.
    verify_deadline_ms: float = 0.0
    # device-resident MVCC state (fabric_tpu/state): keep an LRU
    # key-range cache of committed versions resident in DEVICE memory
    # across blocks — the fused stage-2 program reads them there, the
    # per-block host state_fill shrinks to the miss set, and each
    # committed block's write-set applies as a delta scatter at the
    # commit boundary.  Default OFF: CPU/tier-1 hosts keep the exact
    # host state_fill path (which also stays as the bit-equal
    # per-block fallback for misses, range queries, eviction pressure
    # and device failures).
    state_resident: bool = False
    # resident version-table budget in MiB of device memory (12 bytes
    # per cached key; the slot count rounds down to a power of two so
    # mesh shards divide it exactly)
    state_resident_mb: int = 64
    # key-range granularity: keys hash into 2^bits ranges, the LRU
    # admission/eviction unit — fewer bits = coarser ranges (bulkier
    # evictions, cheaper bookkeeping), more bits = finer working-set
    # tracking
    state_resident_range_bits: int = 12
    # validation sidecar, client side (fabric_tpu/sidecar): with an
    # endpoint set, every channel's validator ships its signature
    # batches to the sidecar's shared device fabric instead of owning
    # a local device lane (SidecarValidator); "" = in-process device
    # lane, today's behavior.  Weight is this peer's fair-share claim
    # in the sidecar's weighted-deficit-round-robin scheduler, and
    # sidecar_recovery_s paces the degrade latch's re-attach probes
    # after a sidecar loss (blocks ride the local CPU fallback while
    # detached — latency degrades, liveness never does).
    sidecar_endpoint: str = ""
    sidecar_weight: float = 1.0
    sidecar_recovery_s: float = 5.0
    # validation sidecar, server side: a host:port makes THIS process
    # also serve a validation sidecar from its device fabric (the
    # many-peers-one-pod shape; `python -m fabric_tpu.cli
    # sidecar-serve` runs it standalone).  queue_blocks bounds each
    # tenant's admission queue (a full queue answers a typed BUSY
    # frame — explicit backpressure, not unbounded buffering) and
    # sidecar_coalesce caps how many cross-tenant batches merge into
    # one padded device dispatch.
    sidecar_listen: str = ""
    sidecar_queue_blocks: int = 8
    sidecar_coalesce: int = 4
    # traffic autopilot (fabric_tpu/control/autopilot.py): closed-loop
    # overload control — a periodic controller reads trailing SLO burn
    # rates, scheduler queue-age/BUSY telemetry and pipeline overlap
    # coverage, and actuates coalesce_blocks / verify_chunk /
    # pipeline_depth / sidecar tenant weights + shed mode through
    # their runtime setters, governed by hysteresis bands, per-knob
    # cooldowns, a max-one-step-per-tick rule and hard clamps.  OFF by
    # default: tier-1 and CPU hosts keep the exact static path.
    autopilot: bool = False
    # seconds between controller ticks (the decision cadence; each
    # tick actuates at most one knob step)
    autopilot_tick_s: float = 1.0
    # per-knob min/max clamp spec (autopilot.parse_knob_specs), e.g.
    # 'coalesce_blocks:min=0:max=8;verify_chunk:min=512:max=4096;
    # pipeline_depth:min=2:max=4;weight:min=0.125:max=8'.  Empty =
    # the validated defaults; named knobs override per-key.
    autopilot_knobs: str = ""
    # device-batched endorsement signing (peer/signlane.py SignBatcher
    # + ops/p256sign.py): with sign_device on, concurrent ESCC sign
    # requests from the Endorse RPC and the gateway coalesce into ONE
    # padded device sign dispatch (fixed-base k·G comb ladder, RFC 6979
    # deterministic nonces — bit-equal to the serial signer).  A full
    # admission queue answers a typed BUSY (429 proposal response with
    # a retry hint) instead of buffering.  Default OFF: CPU/tier-1
    # hosts keep the exact serial crypto/identity.py signing path.
    sign_device: bool = False
    # most sign requests coalesced per device flush (the autopilot's
    # `sign_batch_max` knob actuates this at flush boundaries)
    sign_batch_max: int = 256
    # ms the flusher lingers after the first pending request before
    # dispatching a partial batch (0 = dispatch immediately)
    sign_batch_wait_ms: float = 2.0
    # verify-after-sign self-check: every fresh sign batch re-verifies
    # through the device verify lane (ops/p256v3.verify_launch) before
    # any signature leaves the peer — one extra device dispatch per
    # sign batch buys a hard guarantee against corrupt signatures
    sign_self_check: bool = False
    # chaos fault plan (fabric_tpu/faults): spec string arming named
    # injection points, e.g.
    # 'validator.verify_launch:raise:n=3;deliver.read:disconnect:n=1'.
    # Staging/soak rigs only; empty = no injection (and fire() costs
    # one attribute read).  FABTPU_FAULTS overrides like any scalar.
    faults: str = ""
    # chaincode install surface (peer/node.py _on_install)
    max_package_size: int = DEFAULT_MAX_PACKAGE_SIZE
    install_require_admin: bool = False


@dataclass
class OrdererConfig:
    """The orderer's knob surface (orderer/common/localconfig)."""

    id: str = ""
    data_dir: str = ""
    msp_id: str = ""
    msp_dir: str = ""
    host: str = "127.0.0.1"
    port: int = 0
    operations_port: int | None = None
    cluster: dict = field(default_factory=dict)   # id -> [host, port]
    channels: list = field(default_factory=list)  # [ChannelRef | name]
    tls: TlsConfig | None = None
    # blockcutter (orderer.yaml BatchSize/BatchTimeout)
    max_message_count: int = 500
    batch_timeout_s: float = 0.2
    # consensus
    consensus: str = "raft"          # "raft" | "bft"
    view_timeout: float = 2.0
    wal_retention: int = 256
    broadcast_rate: float = 0.0      # msgs/s per channel; 0 = unlimited


_REQUIRED = {"id", "data_dir"}


def _is_union(origin) -> bool:
    import types
    import typing

    # PEP 604 unions (int | None) have origin types.UnionType, NOT
    # typing.Union — missing that silently skipped Optional fields
    return origin is typing.Union or origin is types.UnionType


def _coerce(name: str, val, typ):
    """Type-check/coerce one scalar with an operator-grade error."""
    import typing

    origin = typing.get_origin(typ)
    if _is_union(origin):  # Optional[...]
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if val is None:
            return None
        return _coerce(name, val, args[0])
    if typ is float and isinstance(val, int):
        return float(val)
    if typ is int and isinstance(val, bool):
        raise ConfigError(f"key '{name}': expected int, got bool")
    if typ in (int, float, str, bool) and not isinstance(val, typ):
        # env vars arrive as strings: coerce them
        if isinstance(val, str) and typ in (int, float):
            try:
                return typ(val)
            except ValueError:
                raise ConfigError(
                    f"key '{name}': cannot parse {val!r} as {typ.__name__}"
                ) from None
        if isinstance(val, str) and typ is bool:
            if val.lower() in ("true", "1", "yes"):
                return True
            if val.lower() in ("false", "0", "no"):
                return False
            raise ConfigError(
                f"key '{name}': cannot parse {val!r} as bool"
            )
        raise ConfigError(
            f"key '{name}': expected {typ.__name__}, "
            f"got {type(val).__name__} ({val!r})"
        )
    return val


def _build(cls, raw: dict, prefix: str = ""):
    """dict → dataclass with unknown-key / type errors naming keys."""
    if not isinstance(raw, dict):
        raise ConfigError(
            f"section '{prefix or cls.__name__}': expected an object, "
            f"got {type(raw).__name__}"
        )
    fields = {f.name: f for f in dataclasses.fields(cls)}
    out = {}
    for key, val in raw.items():
        if key not in fields:
            hint = difflib.get_close_matches(key, fields, n=1)
            did = f" — did you mean '{hint[0]}'?" if hint else ""
            raise ConfigError(
                f"unknown key '{prefix}{key}' in {cls.__name__}{did}"
            )
        f = fields[key]
        qual = f"{prefix}{key}"
        if key == "tls":
            out[key] = None if val in (None, {}) else _build(
                TlsConfig, val, prefix=f"{qual}."
            )
        elif key == "channels":
            out[key] = [
                c if isinstance(c, str)
                else _build(ChannelRef, c, prefix=f"{qual}[].")
                for c in _want_list(qual, val)
            ]
        elif key == "chaincodes":
            out[key] = [
                _build(ChaincodeRef, c, prefix=f"{qual}[].")
                for c in _want_list(qual, val)
            ]
        elif key == "peers":
            out[key] = [
                _build(PeerRef, c, prefix=f"{qual}[].")
                for c in _want_list(qual, val)
            ]
        elif key in ("org_msps",):
            out[key] = _want_list(qual, val)
        elif key == "cluster":
            if not isinstance(val, dict):
                raise ConfigError(f"key '{qual}': expected an object")
            out[key] = {k: tuple(v) for k, v in val.items()}
        else:
            out[key] = _coerce(qual, val, f.type if not isinstance(
                f.type, str) else _ANNOT[cls.__name__][key])
    return cls(**out)


def _want_list(name, val):
    if not isinstance(val, list):
        raise ConfigError(f"key '{name}': expected a list")
    return val


# dataclass annotations arrive as strings under
# `from __future__ import annotations` — resolve them once
import typing as _t

_ANNOT = {
    cls.__name__: _t.get_type_hints(cls)
    for cls in (PeerConfig, OrdererConfig, TlsConfig, ChannelRef,
                ChaincodeRef, PeerRef)
}

ENV_PREFIX = "FABTPU_"


def _apply_env(cfg, environ=None):
    """FABTPU_<FIELD> (and FABTPU_TLS_<FIELD>) override scalars —
    the CORE_/ORDERER_ env-override convention."""
    env = os.environ if environ is None else environ
    hints = _ANNOT[type(cfg).__name__]
    for f in dataclasses.fields(cfg):
        typ = hints[f.name]
        key = ENV_PREFIX + f.name.upper()
        if _is_union(_t.get_origin(typ)):
            # only SCALAR unions (Optional[int] etc.) are env-settable:
            # an env string can never construct Optional[TlsConfig] —
            # letting it through would assign the raw string (the
            # ADVICE round-5 bug) and crash far away with
            # AttributeError instead of an error naming the key
            args = [a for a in _t.get_args(typ) if a is not type(None)]
            if len(args) != 1 or args[0] not in (int, float, str, bool):
                if key in env:
                    raise ConfigError(
                        f"env override '{key}' cannot set non-scalar "
                        f"field '{f.name}' — use the config file (or "
                        f"{ENV_PREFIX}TLS_* for the tls section)"
                    )
                continue
        elif typ not in (int, float, str, bool):
            if key in env:
                raise ConfigError(
                    f"env override '{key}' cannot set non-scalar "
                    f"field '{f.name}' — use the config file"
                )
            continue
        if key in env:
            setattr(cfg, f.name, _coerce(f"${key}", env[key], typ))
    tls_hints = _ANNOT["TlsConfig"]
    tls_envs = {
        k: v for k, v in env.items()
        if k.startswith(ENV_PREFIX + "TLS_")
    }
    if tls_envs:
        if cfg.tls is None:
            cfg.tls = TlsConfig()
        for k, v in tls_envs.items():
            fname = k[len(ENV_PREFIX) + 4:].lower()
            if fname not in tls_hints:
                raise ConfigError(f"unknown env override '{k}'")
            setattr(cfg.tls, fname, v)
    return cfg


def _load(cls, source, environ=None):
    if isinstance(source, str):
        try:
            with open(source) as f:
                raw = json.load(f)
        except json.JSONDecodeError as e:
            raise ConfigError(f"{source}: invalid JSON: {e}") from None
    else:
        raw = source
    cfg = _build(cls, raw)
    _apply_env(cfg, environ)
    required = set(_REQUIRED)
    if cls is PeerConfig:
        # the peer cannot start without a signing identity (the
        # orderer can — unsigned dev channels exist)
        required |= {"msp_dir", "msp_id"}
    missing = [k for k in required if not getattr(cfg, k)]
    if missing:
        raise ConfigError(
            f"{cls.__name__}: missing required key(s): "
            + ", ".join(sorted(missing))
        )
    if cfg.tls is not None:
        tmiss = [k for k in ("cert", "key", "ca")
                 if not getattr(cfg.tls, k)]
        if tmiss and len(tmiss) < 3:
            raise ConfigError(
                "tls section: cert, key, and ca must be set together; "
                "missing: " + ", ".join(tmiss)
            )
        if len(tmiss) == 3:
            cfg.tls = None  # an all-empty section means no TLS
    if isinstance(cfg, PeerConfig) and cfg.pipeline_depth < 1:
        raise ConfigError(
            f"key 'pipeline_depth': must be >= 1 (1 = serial, 2 = "
            f"classic overlap, N = deep window), got {cfg.pipeline_depth}"
        )
    if isinstance(cfg, PeerConfig) and cfg.apply_queue_blocks < 1:
        raise ConfigError(
            f"key 'apply_queue_blocks': must be >= 1 trailing batch "
            f"(the bound is what keeps apply lag and crash-recovery "
            f"replay finite), got {cfg.apply_queue_blocks}"
        )
    if isinstance(cfg, PeerConfig) and cfg.host_stage_mode not in (
            "thread", "process"):
        raise ConfigError(
            f"key 'host_stage_mode': must be 'thread' or 'process', "
            f"got {cfg.host_stage_mode!r}"
        )
    if isinstance(cfg, PeerConfig) and cfg.vitals_interval_s < 0:
        raise ConfigError(
            f"key 'vitals_interval_s': must be >= 0 seconds (0 = "
            f"recorder off), got {cfg.vitals_interval_s}"
        )
    if isinstance(cfg, PeerConfig) and cfg.vitals_retention < 1:
        raise ConfigError(
            f"key 'vitals_retention': must be >= 1 points per series, "
            f"got {cfg.vitals_retention}"
        )
    if isinstance(cfg, PeerConfig) and cfg.sign_batch_max < 1:
        raise ConfigError(
            f"key 'sign_batch_max': must be >= 1 sign request per "
            f"device flush, got {cfg.sign_batch_max}"
        )
    if isinstance(cfg, PeerConfig) and cfg.sign_batch_wait_ms < 0:
        raise ConfigError(
            f"key 'sign_batch_wait_ms': must be >= 0 ms (0 = flush "
            f"immediately), got {cfg.sign_batch_wait_ms}"
        )
    if isinstance(cfg, PeerConfig) and cfg.state_resident_mb < 1:
        raise ConfigError(
            f"key 'state_resident_mb': must be >= 1 MiB of device "
            f"memory for the resident version table, "
            f"got {cfg.state_resident_mb}"
        )
    if isinstance(cfg, PeerConfig) and not (
            1 <= cfg.state_resident_range_bits <= 24):
        raise ConfigError(
            f"key 'state_resident_range_bits': must be in [1, 24] "
            f"(keys hash into 2^bits LRU ranges), "
            f"got {cfg.state_resident_range_bits}"
        )
    if isinstance(cfg, PeerConfig) and cfg.mesh_shape:
        from fabric_tpu.parallel.topology import parse_mesh_shape

        try:
            parse_mesh_shape(cfg.mesh_shape)
        except ValueError as e:
            raise ConfigError(f"key 'mesh_shape': {e}") from None
    if isinstance(cfg, PeerConfig) and cfg.mesh_distributed \
            and not cfg.mesh_coordinator:
        raise ConfigError(
            "key 'mesh_distributed': requires 'mesh_coordinator' "
            "(host:port of the jax.distributed rendezvous)"
        )
    if isinstance(cfg, PeerConfig) and cfg.mesh_num_processes < 1:
        raise ConfigError(
            f"key 'mesh_num_processes': must be >= 1 process, "
            f"got {cfg.mesh_num_processes}"
        )
    if isinstance(cfg, PeerConfig) and not (
            0 <= cfg.mesh_process_id < cfg.mesh_num_processes):
        raise ConfigError(
            f"key 'mesh_process_id': must be in [0, "
            f"mesh_num_processes={cfg.mesh_num_processes}), "
            f"got {cfg.mesh_process_id}"
        )
    if isinstance(cfg, PeerConfig) and cfg.autopilot_tick_s <= 0:
        raise ConfigError(
            f"key 'autopilot_tick_s': must be > 0 seconds, "
            f"got {cfg.autopilot_tick_s}"
        )
    if isinstance(cfg, PeerConfig) and (cfg.autopilot
                                        or cfg.autopilot_knobs):
        # validate the knob-clamp spec HERE so a typo surfaces as an
        # operator-grade config error, not an exception mid-start
        from fabric_tpu.control import KnobSpecError, parse_knob_specs

        try:
            parse_knob_specs(cfg.autopilot_knobs)
        except KnobSpecError as e:
            raise ConfigError(f"key 'autopilot_knobs': {e}") from None
    if isinstance(cfg, PeerConfig) and cfg.slos:
        # validate the SLO spec HERE so a typo surfaces as an
        # operator-grade config error, not an exception mid-start
        from fabric_tpu.observe.slo import SloError, parse_slos

        try:
            parse_slos(cfg.slos)
        except SloError as e:
            raise ConfigError(f"key 'slos': {e}") from None
    if isinstance(cfg, OrdererConfig) and cfg.consensus not in (
            "raft", "bft"):
        raise ConfigError(
            f"key 'consensus': must be 'raft' or 'bft', "
            f"got {cfg.consensus!r}"
        )
    return cfg


def load_peer_config(source, environ=None) -> PeerConfig:
    """``source``: path to a JSON file or an already-loaded dict."""
    return _load(PeerConfig, source, environ)


def load_orderer_config(source, environ=None) -> OrdererConfig:
    return _load(OrdererConfig, source, environ)

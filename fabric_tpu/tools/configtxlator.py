"""configtxlator analog: config proto ↔ JSON translation + config
update (delta) computation (reference: internal/configtxlator/update +
the REST tool in cmd/configtxlator; here a library + CLI verbs — no
REST server needed when the CLI is a library call away)."""

from __future__ import annotations

from google.protobuf import json_format

from fabric_tpu.protos import common_pb2, configtx_pb2, orderer_pb2, policies_pb2

_TYPES = {
    "common.Config": configtx_pb2.Config,
    "common.ConfigEnvelope": configtx_pb2.ConfigEnvelope,
    "common.ConfigUpdate": configtx_pb2.ConfigUpdate,
    "common.ConfigUpdateEnvelope": configtx_pb2.ConfigUpdateEnvelope,
    "common.Block": common_pb2.Block,
    "common.Envelope": common_pb2.Envelope,
    "common.Payload": common_pb2.Payload,
    "orderer.ConsensusType": orderer_pb2.ConsensusType,
    "orderer.RaftConfigMetadata": orderer_pb2.RaftConfigMetadata,
    "policies.SignaturePolicyEnvelope": policies_pb2.SignaturePolicyEnvelope,
}


def message_type(name: str):
    try:
        return _TYPES[name]
    except KeyError:
        raise ValueError(
            f"unknown message type {name!r}; known: {sorted(_TYPES)}"
        ) from None


def proto_decode(type_name: str, data: bytes) -> str:
    """Serialized proto → canonical JSON."""
    msg = message_type(type_name)()
    msg.ParseFromString(data)
    return json_format.MessageToJson(
        msg, preserving_proto_field_name=True, sort_keys=True
    )


def proto_encode(type_name: str, json_text: str) -> bytes:
    """JSON → serialized proto (round-trips proto_decode)."""
    msg = message_type(type_name)()
    json_format.Parse(json_text, msg)
    return msg.SerializeToString()


def compute_update(channel_id: str, original: bytes, updated: bytes) -> bytes:
    """Two serialized common.Config snapshots → the serialized
    common.ConfigUpdate delta (read set with version pins + write set)
    — internal/configtxlator/update/update.go Compute."""
    from fabric_tpu.tools import configtxgen as ctg

    cur = configtx_pb2.Config()
    cur.ParseFromString(original)
    new = configtx_pb2.Config()
    new.ParseFromString(updated)
    upd = ctg.compute_update(channel_id, cur, new)
    return upd.SerializeToString()

"""Offline peer-channel operations: rollback / reset / unjoin /
rebuild-dbs (reference: internal/peer/node/{rollback,reset,unjoin,
rebuild_dbs}.go — filesystem surgery on a STOPPED peer's channel
directory; derived databases are rebuilt by replay on next start via
KVLedger.recover, the same recovery machinery crash restarts use)."""

from __future__ import annotations

import os
import shutil
import struct

_LEN = struct.Struct("<I")

# everything except the block segments is derived state
_DERIVED = (
    "state.db", "state.db-wal", "state.db-shm",
    "history.db", "history.db-wal", "history.db-shm",
    "pvtdata.db", "pvtdata.db-wal", "pvtdata.db-shm",
    "transient.db", "transient.db-wal", "transient.db-shm",
    "confighistory.db", "confighistory.db-wal", "confighistory.db-shm",
)


def _drop_derived(channel_dir: str) -> list:
    dropped = []
    for name in _DERIVED:
        p = os.path.join(channel_dir, name)
        if os.path.exists(p):
            os.unlink(p)
            dropped.append(name)
    # the block index (chains/index.db) is derived from the segments
    for idx in ("index.db", "index.db-wal", "index.db-shm"):
        p = os.path.join(channel_dir, "chains", idx)
        if os.path.exists(p):
            os.unlink(p)
            dropped.append(f"chains/{idx}")
    return dropped


def reset(channel_dir: str) -> dict:
    """Drop ALL derived databases (state, history, indexes); block
    segments stay.  Next start replays the chain from block 0
    (node/reset.go)."""
    dropped = _drop_derived(channel_dir)
    return {"channel_dir": channel_dir, "dropped": dropped}


def rebuild_dbs(channel_dir: str) -> dict:
    """Alias surface of the reference's rebuild-dbs (reset keeps the
    same post-condition here: derived DBs rebuilt by replay)."""
    out = reset(channel_dir)
    out["op"] = "rebuild-dbs"
    return out


def unjoin(channel_dir: str) -> dict:
    """Remove the channel entirely from this peer (node/unjoin.go)."""
    if not os.path.isdir(channel_dir):
        raise FileNotFoundError(channel_dir)
    shutil.rmtree(channel_dir)
    return {"channel_dir": channel_dir, "removed": True}


def rollback(channel_dir: str, block_number: int) -> dict:
    """Truncate the chain so ``block_number`` is the LAST block
    (node/rollback.go), dropping every derived DB — the next start
    replays state up to the rollback point.

    Block segments are scanned for the cut point; later segments are
    deleted and the containing segment truncated."""
    dirpath = os.path.join(channel_dir, "chains")
    seg_names = sorted(
        n for n in os.listdir(dirpath)
        if n.startswith("blocks_") and n.endswith(".bin")
    )
    if not seg_names:
        raise FileNotFoundError(f"no block segments under {dirpath}")

    from fabric_tpu.protos import common_pb2

    cut_done = False
    removed_blocks = 0
    for name in seg_names:
        path = os.path.join(dirpath, name)
        if cut_done:
            os.unlink(path)
            continue
        with open(path, "rb") as f:
            blob = f.read()
        off = 0
        keep = None
        while off + _LEN.size <= len(blob):
            (ln,) = _LEN.unpack(blob[off:off + _LEN.size])
            end = off + _LEN.size + ln
            if end > len(blob):
                break
            blk = common_pb2.Block()
            blk.ParseFromString(blob[off + _LEN.size:end])
            if blk.header.number > block_number:
                keep = off
                break
            off = end
        if keep is not None:
            removed_blocks += 1  # at least; exact count not needed
            with open(path, "r+b") as f:
                f.truncate(keep)
            cut_done = True
    _drop_derived(channel_dir)
    return {
        "channel_dir": channel_dir, "rolled_back_to": block_number,
        "truncated": cut_done,
    }

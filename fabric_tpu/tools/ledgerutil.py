"""Offline ledger forensics: verify and compare (the reference's
internal/ledgerutil — `ledgerutil verify/compare/identifytxs`).

Operates on closed ledger directories (a peer's
``<data>/<channel>``): re-checks the block hash chain, the commit-hash
chain, and the TRANSACTIONS_FILTER shape; compare diffs two peers'
ledgers block by block to localize divergence."""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from fabric_tpu import protoutil
from fabric_tpu.ledger.blockstore import BlockStore
from fabric_tpu.protos import common_pb2


@dataclass
class VerifyResult:
    height: int = 0
    errors: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors


def verify_ledger(ledger_dir: str) -> VerifyResult:
    """Walk the block store checking: header numbers, previous-hash
    chaining, data-hash integrity, and commit-hash chaining."""
    import os

    store = BlockStore(os.path.join(ledger_dir, "chains"))
    res = VerifyResult(height=store.height)
    prev_hash = b""
    commit_hash = b""
    try:
        for num in range(store.height):
            blk = store.get_block(num)
            if blk is None:
                boot = store.bootstrap_info()
                if boot and num < boot[0]:
                    continue  # pre-snapshot blocks absent by design
                res.errors.append(f"block {num}: missing")
                continue
            if blk.header.number != num:
                res.errors.append(f"block {num}: header number {blk.header.number}")
            if prev_hash and blk.header.previous_hash != prev_hash:
                res.errors.append(f"block {num}: previous_hash mismatch")
            want_data = protoutil.block_data_hash(blk.data)
            if blk.header.data_hash != want_data:
                res.errors.append(f"block {num}: data_hash mismatch")
            idx = common_pb2.BlockMetadataIndex.COMMIT_HASH
            if len(blk.metadata.metadata) > idx and blk.metadata.metadata[idx]:
                flt = protoutil.get_tx_filter(blk)
                want = hashlib.sha256(
                    commit_hash + protoutil.block_header_hash(blk.header)
                    + bytes(flt)
                ).digest()
                got = blk.metadata.metadata[idx]
                if got != want:
                    res.errors.append(f"block {num}: commit_hash chain broken")
                commit_hash = got
            prev_hash = protoutil.block_header_hash(blk.header)
    finally:
        store.close()
    return res


def compare_ledgers(dir_a: str, dir_b: str) -> dict:
    """Block-level diff of two ledgers; returns the first divergence
    (the reference's compare produces a diff record set)."""
    import os

    sa = BlockStore(os.path.join(dir_a, "chains"))
    sb = BlockStore(os.path.join(dir_b, "chains"))
    try:
        out = {
            "height_a": sa.height, "height_b": sb.height,
            "common_height": min(sa.height, sb.height),
            "first_divergence": None,
            "identical": True,
        }
        for num in range(out["common_height"]):
            a, b = sa.get_block(num), sb.get_block(num)
            ab = a.SerializeToString() if a else b""
            bb = b.SerializeToString() if b else b""
            if ab != bb:
                out["first_divergence"] = num
                out["identical"] = False
                break
        if sa.height != sb.height:
            out["identical"] = False
        return out
    finally:
        sa.close()
        sb.close()

"""configtxgen analog: profiles → genesis config blocks + channel
creation / config-update envelopes.

Reference: internal/configtxgen (profiles from configtx.yaml →
``OutputBlock``), common/configtx (update computation).  Here the
profile is a Python dataclass rather than YAML — the framework is a
library first; the CLI wrapper lives in fabric_tpu/cli.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from fabric_tpu import protoutil
from fabric_tpu.channelconfig import CAP_V2_0, ImplicitMeta, config_policy
from fabric_tpu.crypto import policy as pol
from fabric_tpu.protos import common_pb2, configtx_pb2, orderer_pb2, policies_pb2

IM = policies_pb2.ImplicitMetaPolicy


@dataclass
class OrgProfile:
    msp_id: str
    msp: object  # crypto.msp.MSP
    anchor_peers: list = field(default_factory=list)  # (host, port)


@dataclass
class Profile:
    """One channel's genesis profile (a configtx.yaml profile)."""

    channel_id: str
    application_orgs: list = field(default_factory=list)  # [OrgProfile]
    orderer_orgs: list = field(default_factory=list)
    consensus_type: str = "raft"
    raft_consenters: list = field(default_factory=list)  # [(host, port)]
    max_message_count: int = 500
    preferred_max_bytes: int = 2 * 1024 * 1024
    absolute_max_bytes: int = 10 * 1024 * 1024
    batch_timeout_ms: int = 200
    capabilities: tuple = (CAP_V2_0,)


def _org_group(org: OrgProfile) -> configtx_pb2.ConfigGroup:
    g = configtx_pb2.ConfigGroup(mod_policy="Admins")
    g.values["MSP"].value = org.msp.to_proto().SerializeToString()
    g.values["MSP"].mod_policy = "Admins"
    mid = org.msp_id
    member = pol.SignedBy(pol.Principal(mid, pol.ROLE_MEMBER))
    admin = pol.SignedBy(pol.Principal(mid, pol.ROLE_ADMIN))
    peer = pol.SignedBy(pol.Principal(mid, pol.ROLE_PEER))
    g.policies["Readers"].CopyFrom(config_policy(member))
    g.policies["Writers"].CopyFrom(config_policy(member))
    g.policies["Admins"].CopyFrom(config_policy(admin))
    g.policies["Endorsement"].CopyFrom(config_policy(peer))
    if org.anchor_peers:
        ap = configtx_pb2.AnchorPeers()
        for host, port in org.anchor_peers:
            ap.anchor_peers.add(host=host, port=port)
        g.values["AnchorPeers"].value = ap.SerializeToString()
        g.values["AnchorPeers"].mod_policy = "Admins"
    return g


def _implicit(rule: int, sub: str) -> configtx_pb2.ConfigPolicy:
    return config_policy(ImplicitMeta(rule=rule, sub_policy=sub))


def genesis_config(profile: Profile) -> configtx_pb2.Config:
    root = configtx_pb2.ConfigGroup(mod_policy="Admins")
    caps = configtx_pb2.Capabilities()
    for c in profile.capabilities:
        caps.capabilities[c].SetInParent()
    root.values["Capabilities"].value = caps.SerializeToString()
    root.values["Capabilities"].mod_policy = "Admins"
    root.values["HashingAlgorithm"].value = configtx_pb2.HashingAlgorithm(
        name="SHA256"
    ).SerializeToString()
    root.values["BlockDataHashingStructure"].value = (
        configtx_pb2.BlockDataHashingStructure(width=0xFFFFFFFF).SerializeToString()
    )
    for name, rule, sub in (
        ("Readers", IM.ANY, "Readers"),
        ("Writers", IM.ANY, "Writers"),
        ("Admins", IM.MAJORITY, "Admins"),
    ):
        root.policies[name].CopyFrom(_implicit(rule, sub))

    app = root.groups["Application"]
    app.mod_policy = "Admins"
    app.values["Capabilities"].value = caps.SerializeToString()
    app.values["Capabilities"].mod_policy = "Admins"
    for name, rule, sub in (
        ("Readers", IM.ANY, "Readers"),
        ("Writers", IM.ANY, "Writers"),
        ("Admins", IM.MAJORITY, "Admins"),
        ("Endorsement", IM.MAJORITY, "Endorsement"),
        ("LifecycleEndorsement", IM.MAJORITY, "Endorsement"),
    ):
        app.policies[name].CopyFrom(_implicit(rule, sub))
    for org in profile.application_orgs:
        app.groups[org.msp_id].CopyFrom(_org_group(org))

    ordg = root.groups["Orderer"]
    ordg.mod_policy = "Admins"
    consenters = []
    for c in profile.raft_consenters:
        # (host, port[, serialized_identity[, node_id]]) — BFT channels
        # need the identity to pin the attestation voter set; the node
        # id drives membership reconfiguration
        rc = orderer_pb2.RaftConsenter(host=c[0], port=c[1])
        if len(c) > 2 and c[2]:
            rc.identity = c[2]
        if len(c) > 3 and c[3]:
            rc.id = c[3]
        consenters.append(rc)
    ordg.values["ConsensusType"].value = orderer_pb2.ConsensusType(
        type=profile.consensus_type,
        metadata=orderer_pb2.RaftConfigMetadata(
            consenters=consenters
        ).SerializeToString(),
    ).SerializeToString()
    ordg.values["BatchSize"].value = orderer_pb2.BatchSize(
        max_message_count=profile.max_message_count,
        preferred_max_bytes=profile.preferred_max_bytes,
        absolute_max_bytes=profile.absolute_max_bytes,
    ).SerializeToString()
    ordg.values["BatchTimeout"].value = orderer_pb2.BatchTimeout(
        timeout=f"{profile.batch_timeout_ms}ms"
    ).SerializeToString()
    for name, rule, sub in (
        ("Readers", IM.ANY, "Readers"),
        ("Writers", IM.ANY, "Writers"),
        ("Admins", IM.MAJORITY, "Admins"),
        ("BlockValidation", IM.ANY, "Writers"),
    ):
        ordg.policies[name].CopyFrom(_implicit(rule, sub))
    for org in profile.orderer_orgs:
        ordg.groups[org.msp_id].CopyFrom(_org_group(org))

    return configtx_pb2.Config(sequence=0, channel_group=root)


def genesis_block(profile: Profile) -> common_pb2.Block:
    """Block 0: a CONFIG envelope holding the genesis ConfigEnvelope."""
    config = genesis_config(profile)
    cfg_env = configtx_pb2.ConfigEnvelope(config=config)
    ch = protoutil.make_channel_header(
        common_pb2.HeaderType.CONFIG, profile.channel_id, tx_id=""
    )
    sh = protoutil.make_signature_header(b"", protoutil.random_nonce())
    payload = protoutil.make_payload(ch, sh, cfg_env.SerializeToString())
    env = common_pb2.Envelope(payload=payload.SerializeToString())
    blk = protoutil.new_block(0, b"")
    blk.data.data.append(env.SerializeToString())
    return protoutil.finalize_block(blk)


# ---------------------------------------------------------------------------
# Config updates


def compute_update(channel_id: str, current: configtx_pb2.Config,
                   updated: configtx_pb2.Config) -> configtx_pb2.ConfigUpdate:
    """Minimal read/write-set delta between two configs (the
    configtxlator compute-update analog): read_set references every
    group on the path to a change at its current version; write_set
    carries changed elements with bumped versions."""
    upd = configtx_pb2.ConfigUpdate(channel_id=channel_id)

    def diff(cur: configtx_pb2.ConfigGroup, new: configtx_pb2.ConfigGroup,
             rd: configtx_pb2.ConfigGroup, wr: configtx_pb2.ConfigGroup) -> bool:
        changed = False
        rd.version = cur.version
        wr.version = cur.version
        wr.mod_policy = new.mod_policy
        # deletions: a removed child means this group's version bumps
        # and the write set lists the EXACT surviving membership
        # (authorize_update applies bumped groups as exact-membership,
        # common/configtx/update.go configmap semantics)
        deleted = (
            (set(cur.groups) - set(new.groups))
            | (set(cur.values) - set(new.values))
            | (set(cur.policies) - set(new.policies))
        )
        if deleted:
            changed = True
            wr.version = cur.version + 1
            for name, ng in new.groups.items():
                if name in cur.groups:
                    wr.groups[name].CopyFrom(ng)
                    wr.groups[name].version = cur.groups[name].version
            for name, nv in new.values.items():
                if name in cur.values:
                    wr.values[name].CopyFrom(nv)
                    wr.values[name].version = cur.values[name].version
            for name, np2 in new.policies.items():
                if name in cur.policies:
                    wr.policies[name].CopyFrom(np2)
                    wr.policies[name].version = cur.policies[name].version
        for name, ng in new.groups.items():
            if name in cur.groups:
                sub_changed = diff(cur.groups[name], ng,
                                   rd.groups[name], wr.groups[name])
                if not sub_changed:
                    del rd.groups[name]
                    # with deletions, unchanged siblings stay in the
                    # write set — bumped groups are exact-membership
                    if not deleted:
                        del wr.groups[name]
                changed |= sub_changed
            else:
                wr.groups[name].CopyFrom(ng)
                wr.groups[name].version = 0
                changed = True
        for name, nv in new.values.items():
            cv = cur.values.get(name)
            if cv is None:
                wr.values[name].CopyFrom(nv)
                wr.values[name].version = 0
                changed = True
            elif cv.value != nv.value or cv.mod_policy != nv.mod_policy:
                wr.values[name].CopyFrom(nv)
                wr.values[name].version = cv.version + 1
                changed = True
        for name, np_ in new.policies.items():
            cp = cur.policies.get(name)
            if cp is None:
                wr.policies[name].CopyFrom(np_)
                wr.policies[name].version = 0
                changed = True
            elif cp.SerializeToString() != np_.SerializeToString():
                wr.policies[name].CopyFrom(np_)
                wr.policies[name].version = cp.version + 1
                changed = True
        return changed

    diff(current.channel_group, updated.channel_group,
         upd.read_set, upd.write_set)
    return upd


def sign_update(update: configtx_pb2.ConfigUpdate,
                signers) -> configtx_pb2.ConfigUpdateEnvelope:
    """Wrap + sign: each signer adds a ConfigSignature over
    signature_header ‖ config_update."""
    env = configtx_pb2.ConfigUpdateEnvelope(
        config_update=update.SerializeToString()
    )
    for signer in signers:
        sh = protoutil.make_signature_header(
            signer.serialized, protoutil.random_nonce()
        ).SerializeToString()
        env.signatures.add(
            signature_header=sh,
            signature=signer.sign(sh + env.config_update),
        )
    return env


def config_tx(channel_id: str, new_config: configtx_pb2.Config,
              update_env: configtx_pb2.ConfigUpdateEnvelope,
              signer=None) -> common_pb2.Envelope:
    """A CONFIG envelope carrying ConfigEnvelope{config, last_update}
    — what the orderer emits after processing a config update."""
    upd_payload = protoutil.make_payload(
        protoutil.make_channel_header(
            common_pb2.HeaderType.CONFIG_UPDATE, channel_id, tx_id=""
        ),
        protoutil.make_signature_header(
            signer.serialized if signer else b"",
            protoutil.random_nonce(),
        ),
        update_env.SerializeToString(),
    )
    last_update = common_pb2.Envelope(payload=upd_payload.SerializeToString())
    if signer is not None:
        last_update.signature = signer.sign(last_update.payload)
    cfg_env = configtx_pb2.ConfigEnvelope(config=new_config, last_update=last_update)

    nonce = protoutil.random_nonce()
    creator = signer.serialized if signer else b""
    ch = protoutil.make_channel_header(
        common_pb2.HeaderType.CONFIG, channel_id,
        tx_id=protoutil.compute_tx_id(nonce, creator),
    )
    sh = protoutil.make_signature_header(creator, nonce)
    payload = protoutil.make_payload(ch, sh, cfg_env.SerializeToString())
    if signer is not None:
        return protoutil.sign_envelope(payload, signer)
    return common_pb2.Envelope(payload=payload.SerializeToString())

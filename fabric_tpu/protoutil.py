"""Wire-format helpers (the analog of the reference's protoutil/ package).

Builders and extractors for envelopes, transactions and blocks, plus
the two hashes that anchor the chain:

* block data hash = SHA-256 over the concatenated serialized envelopes
  (reference: protoutil/blockutils.go BlockDataHash), batchable on TPU
  via fabric_tpu.ops.sha256;
* block header hash = SHA-256 over the ASN.1-DER encoding of
  (number, previous_hash, data_hash) (reference:
  protoutil/blockutils.go BlockHeaderBytes) — hand-rolled DER here,
  ~20 lines, no ASN.1 dependency.

Also the TRANSACTIONS_FILTER helpers (reference: internal/pkg/txflags)
— the validity-code byte array the TPU validator writes back into
block metadata.
"""

from __future__ import annotations

import hashlib
import os
import time

from google.protobuf.message import DecodeError

from fabric_tpu.protos import common_pb2, proposal_pb2, transaction_pb2


# ---------------------------------------------------------------------------
# Minimal DER (only what the header hash needs)


def _der_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _der_int(x: int) -> bytes:
    if x == 0:
        body = b"\x00"
    else:
        body = x.to_bytes((x.bit_length() + 8) // 8, "big")  # leading 0 if MSB set
        if body[0] == 0 and len(body) > 1 and body[1] < 0x80:
            body = body[1:]
    return b"\x02" + _der_len(len(body)) + body


def _der_octets(b: bytes) -> bytes:
    return b"\x04" + _der_len(len(b)) + b


def block_header_bytes(header: common_pb2.BlockHeader) -> bytes:
    body = (
        _der_int(header.number)
        + _der_octets(header.previous_hash)
        + _der_octets(header.data_hash)
    )
    return b"\x30" + _der_len(len(body)) + body


def block_header_hash(header: common_pb2.BlockHeader) -> bytes:
    return hashlib.sha256(block_header_bytes(header)).digest()


def block_data_hash(data: common_pb2.BlockData) -> bytes:
    return hashlib.sha256(b"".join(data.data)).digest()


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def block_header_data_bytes(block: common_pb2.Block) -> bytes:
    """Serialized form of the block's header + data fields (protobuf
    fields 1 and 2) WITHOUT the metadata.  The commit path mutates
    only metadata (tx filter, commit hash, signatures), so the
    prefetch thread can serialize the immutable 99% of the block once
    and the committer splices fresh metadata on
    (``append_block_metadata``) — the full-block SerializeToString was
    ~7 ms/block of committer-thread time."""
    h = block.header.SerializeToString()
    # BlockData = repeated bytes (field 1): frame the ALREADY-serialized
    # envelopes by hand instead of paying upb to re-walk ~1.5 MB
    frames = []
    for env in block.data.data:
        frames.append(b"\x0a" + _pb_varint(len(env)))
        frames.append(env)
    d = b"".join(frames)
    out = b"\x0a" + _pb_varint(len(h)) + h
    if d:  # upb omits an unset empty submessage; match parse semantics
        out += b"\x12" + _pb_varint(len(d)) + d
    return out


def append_block_metadata(hd_bytes: bytes, block: common_pb2.Block) -> bytes:
    """``block_header_data_bytes`` output + the block's CURRENT
    metadata (field 3) → bytes that parse identically to
    block.SerializeToString()."""
    m = block.metadata.SerializeToString()
    return hd_bytes + b"\x1a" + _pb_varint(len(m)) + m


# ---------------------------------------------------------------------------
# IDs, nonces, signed data


def random_nonce() -> bytes:
    return os.urandom(24)


def compute_tx_id(nonce: bytes, creator: bytes) -> str:
    return hashlib.sha256(nonce + creator).hexdigest()


def serialized_identity(msp_id: str, cert_pem: bytes) -> bytes:
    return common_pb2.SerializedIdentity(mspid=msp_id, id_bytes=cert_pem).SerializeToString()


class SignedData:
    """(data, identity, signature) triple — the unit the policy engine
    evaluates (reference: protoutil/signeddata.go:25-31)."""

    __slots__ = ("data", "identity", "signature")

    def __init__(self, data: bytes, identity: bytes, signature: bytes):
        self.data = data
        self.identity = identity
        self.signature = signature


def envelope_as_signed_data(env: common_pb2.Envelope) -> SignedData:
    payload = common_pb2.Payload()
    payload.ParseFromString(env.payload)
    sh = common_pb2.SignatureHeader()
    sh.ParseFromString(payload.header.signature_header)
    return SignedData(env.payload, sh.creator, env.signature)


# ---------------------------------------------------------------------------
# Header/envelope builders


def make_channel_header(
    htype: int,
    channel_id: str,
    tx_id: str = "",
    epoch: int = 0,
    extension: bytes = b"",
    version: int = 0,
) -> common_pb2.ChannelHeader:
    ch = common_pb2.ChannelHeader(
        type=htype,
        version=version,
        channel_id=channel_id,
        tx_id=tx_id,
        epoch=epoch,
        extension=extension,
    )
    now = time.time()
    ch.timestamp.seconds = int(now)
    ch.timestamp.nanos = int((now % 1) * 1e9)
    return ch


def make_signature_header(creator: bytes, nonce: bytes) -> common_pb2.SignatureHeader:
    return common_pb2.SignatureHeader(creator=creator, nonce=nonce)


def make_payload(ch, sh, data: bytes) -> common_pb2.Payload:
    return common_pb2.Payload(
        header=common_pb2.Header(
            channel_header=ch.SerializeToString(),
            signature_header=sh.SerializeToString(),
        ),
        data=data,
    )


def sign_envelope(payload: common_pb2.Payload, signer) -> common_pb2.Envelope:
    """signer: object with .sign(bytes) -> bytes."""
    pb = payload.SerializeToString()
    return common_pb2.Envelope(payload=pb, signature=signer.sign(pb))


def unmarshal(msg_cls, data: bytes):
    m = msg_cls()
    m.ParseFromString(data)
    return m


# ---------------------------------------------------------------------------
# Block assembly


def new_block(number: int, previous_hash: bytes) -> common_pb2.Block:
    blk = common_pb2.Block()
    blk.header.number = number
    blk.header.previous_hash = previous_hash
    for _ in range(len(common_pb2.BlockMetadataIndex.keys())):
        blk.metadata.metadata.append(b"")
    return blk


def finalize_block(blk: common_pb2.Block) -> common_pb2.Block:
    blk.header.data_hash = block_data_hash(blk.data)
    return blk


# ---------------------------------------------------------------------------
# Block attestation (reference: blockwriter addBlockSignature,
# orderer/common/multichannel/blockwriter.go; verify side
# common/deliverclient/block_verification.go:243 VerifyBlock)


def sign_block(blk: common_pb2.Block, signer) -> None:
    """Append the orderer's signature to the SIGNATURES metadata.

    Signed bytes = metadata.value ‖ signature_header ‖ header_hash —
    binding the signature to THIS block's header (and therefore, via
    data_hash and previous_hash, to its content and chain position).
    """
    import os as _os

    idx = common_pb2.BlockMetadataIndex.SIGNATURES
    md = common_pb2.Metadata()
    if len(blk.metadata.metadata) > idx and blk.metadata.metadata[idx]:
        md.ParseFromString(blk.metadata.metadata[idx])
    sh = common_pb2.SignatureHeader(
        creator=signer.serialized, nonce=_os.urandom(24)
    ).SerializeToString()
    sig = signer.sign(md.value + sh + block_header_hash(blk.header))
    md.signatures.add(signature_header=sh, signature=sig)
    while len(blk.metadata.metadata) <= idx:
        blk.metadata.metadata.append(b"")
    blk.metadata.metadata[idx] = md.SerializeToString()


def block_signed_data(blk: common_pb2.Block) -> list:
    """SIGNATURES metadata → [(creator_identity_bytes, signed_bytes,
    signature)] for policy evaluation at deliver time."""
    idx = common_pb2.BlockMetadataIndex.SIGNATURES
    if len(blk.metadata.metadata) <= idx or not blk.metadata.metadata[idx]:
        return []
    md = common_pb2.Metadata()
    md.ParseFromString(blk.metadata.metadata[idx])
    hh = block_header_hash(blk.header)
    out = []
    for ms in md.signatures:
        try:
            sh = unmarshal(common_pb2.SignatureHeader, ms.signature_header)
        except DecodeError:
            continue  # malformed attestation: contributes no signature
        out.append((sh.creator, md.value + ms.signature_header + hh, ms.signature))
    return out


# ---------------------------------------------------------------------------
# Transaction extraction (the commit pipeline's parse path)


class TxParseError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code


def extract_envelope(block: common_pb2.Block, idx: int) -> common_pb2.Envelope:
    return unmarshal(common_pb2.Envelope, block.data.data[idx])


def extract_action(env: common_pb2.Envelope, parsed=None):
    """Envelope → (channel_header, signature_header, ChaincodeActionPayload,
    ProposalResponsePayload, ChaincodeAction) for an endorser tx.

    ``parsed``: optional already-decoded (payload, ch, sh) triple — the
    validator's parse phase decodes them once for the signature batch
    and must not pay the unmarshal again per tx.

    Raises TxParseError with the matching TxValidationCode on malformed
    structures (reference: core/common/validation/msgvalidation.go:248).
    """
    C = transaction_pb2.TxValidationCode
    if not env.payload:
        raise TxParseError(C.NIL_ENVELOPE, "empty payload")
    try:
        if parsed is not None:
            payload, ch, sh = parsed
        else:
            payload = unmarshal(common_pb2.Payload, env.payload)
            ch = unmarshal(common_pb2.ChannelHeader, payload.header.channel_header)
            sh = unmarshal(common_pb2.SignatureHeader, payload.header.signature_header)
    except Exception as e:
        raise TxParseError(C.BAD_PAYLOAD, f"bad payload: {e}") from e
    if ch.type != common_pb2.HeaderType.ENDORSER_TRANSACTION:
        raise TxParseError(C.UNKNOWN_TX_TYPE, f"type {ch.type}")
    try:
        tx = unmarshal(transaction_pb2.Transaction, payload.data)
        if not tx.actions:
            raise TxParseError(C.NIL_TXACTION, "no actions")
        cap = unmarshal(
            transaction_pb2.ChaincodeActionPayload, tx.actions[0].payload
        )
        prp = unmarshal(
            proposal_pb2.ProposalResponsePayload,
            cap.action.proposal_response_payload,
        )
        cca = unmarshal(proposal_pb2.ChaincodeAction, prp.extension)
    except TxParseError:
        raise
    except Exception as e:
        raise TxParseError(C.BAD_PAYLOAD, f"bad tx: {e}") from e
    return ch, sh, cap, prp, cca


# ---------------------------------------------------------------------------
# TRANSACTIONS_FILTER (reference: internal/pkg/txflags/validation_flags.go)


def new_tx_filter(n: int) -> bytearray:
    return bytearray([transaction_pb2.TxValidationCode.NOT_VALIDATED] * n)


def set_tx_filter(block: common_pb2.Block, flags: bytes) -> None:
    idx = common_pb2.BlockMetadataIndex.TRANSACTIONS_FILTER
    while len(block.metadata.metadata) <= idx:
        block.metadata.metadata.append(b"")
    block.metadata.metadata[idx] = bytes(flags)


def get_tx_filter(block: common_pb2.Block) -> bytes:
    idx = common_pb2.BlockMetadataIndex.TRANSACTIONS_FILTER
    if len(block.metadata.metadata) > idx and block.metadata.metadata[idx]:
        return block.metadata.metadata[idx]
    return bytes(new_tx_filter(len(block.data.data)))


def tx_flag_is_valid(flags: bytes, i: int) -> bool:
    return flags[i] == transaction_pb2.TxValidationCode.VALID

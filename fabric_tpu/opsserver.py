"""Operations HTTP server: /metrics, /healthz, /logspec, /version,
/trace, /slo, /autopilot, /vitals, /launches, /txflow, /debug.

Reference: core/operations/system.go:89-209 — every peer and orderer
process runs one (internal/peer/node/start.go:232-241,
orderer/common/server/main.go:94-101).  Health checkers register by
name and are polled on /healthz (docker/couchdb register themselves in
the reference; here ledgers, raft chains and the RPC server do).
/logspec GET/PUT adjusts live logging levels (flogging's
FABRIC_LOGGING_SPEC semantics over python logging)."""

from __future__ import annotations

import asyncio
import json
import logging

from fabric_tpu.ops_metrics import Registry, global_registry

VERSION = "fabric-tpu 0.3.0"


class HealthRegistry:
    def __init__(self):
        self._checkers: dict[str, object] = {}

    def register(self, name: str, checker) -> None:
        """checker: zero-arg callable → None/True if healthy, raises or
        returns a failure reason string otherwise."""
        self._checkers[name] = checker

    def check(self) -> tuple[bool, dict]:
        failures = {}
        for name, fn in self._checkers.items():
            try:
                res = fn()
                if res not in (None, True):
                    failures[name] = str(res)
            except Exception as e:
                failures[name] = f"{type(e).__name__}: {e}"
        return (not failures), failures


class OperationsServer:
    """Minimal asyncio HTTP/1.1 server (stdlib-only on purpose: the
    control plane must not drag in web frameworks)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry: Registry | None = None,
                 health: HealthRegistry | None = None,
                 tracer=None, slo=None, autopilot=None,
                 vitals=None, blackbox=None, launches=None,
                 txflow=None):
        self.host, self.port = host, port
        self.registry = registry or global_registry()
        self.health = health or HealthRegistry()
        if tracer is None:
            from fabric_tpu.observe import global_tracer

            tracer = global_tracer()
        self.tracer = tracer  # /trace: the block-commit flight recorder
        if slo is None:
            from fabric_tpu.observe.slo import global_engine

            slo = global_engine()
        self.slo = slo        # /slo: the burn-rate engine
        # /autopilot: the traffic controller (None = resolve the
        # process-global handle lazily per request, so a controller
        # armed after the ops server starts is still served)
        self.autopilot = autopilot
        # /vitals: the flight-data recorder — metrics time-series
        # sampler + black-box incident index (both default to lazy
        # process-global resolution, like /autopilot)
        self.vitals = vitals
        self.blackbox = blackbox
        # /launches: the device-time launch ledger (None = lazy
        # process-global resolution, like /autopilot and /vitals)
        self.launches = launches
        # /txflow: the per-tx flow journal (None = lazy process-global
        # resolution, like /launches)
        self.txflow = txflow
        self._server: asyncio.AbstractServer | None = None

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter):
        try:
            req = await reader.readline()
            parts = req.decode("latin1").split()
            if len(parts) < 2:
                return
            method, path = parts[0], parts[1]
            headers = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin1").partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            n = int(headers.get("content-length", "0") or "0")
            if n:
                body = await reader.readexactly(n)
            routed = self._route(method, path, body)
            if callable(routed):  # async route (live profiling window)
                try:
                    text = await routed()
                    status, ctype, payload = 200, "text/plain", text.encode()
                except Exception as e:
                    status, ctype, payload = (
                        500, "application/json",
                        json.dumps({"error": str(e)}).encode(),
                    )
            else:
                status, ctype, payload = routed
            writer.write(
                b"HTTP/1.1 %d %s\r\nContent-Type: %s\r\n"
                b"Content-Length: %d\r\nConnection: close\r\n\r\n"
                % (status, b"OK" if status == 200 else b"ERR",
                   ctype.encode(), len(payload))
            )
            writer.write(payload)
            await writer.drain()
        except (ConnectionError, OSError, RuntimeError):
            pass  # client disconnected mid-response
        finally:
            writer.close()

    def _route(self, method: str, path: str, body: bytes):
        if path == "/metrics":
            return 200, "text/plain; version=0.0.4", self.registry.render().encode()
        if path == "/healthz":
            ok, failures = self.health.check()
            payload = json.dumps(
                {"status": "OK" if ok else "Service Unavailable",
                 "failed_checks": [
                     {"component": k, "reason": v} for k, v in failures.items()
                 ]}
            ).encode()
            return (200 if ok else 503), "application/json", payload
        if path == "/version":
            return 200, "application/json", json.dumps(
                {"Version": VERSION}
            ).encode()
        if path == "/logspec":
            if method == "GET":
                root = logging.getLogger("fabric_tpu")
                return 200, "application/json", json.dumps(
                    {"spec": logging.getLevelName(
                        root.level or logging.WARNING)}
                ).encode()
            if method == "PUT":
                try:
                    spec = json.loads(body)["spec"]
                    apply_logspec(spec)
                    return 204, "application/json", b""
                except Exception as e:
                    return 400, "application/json", json.dumps(
                        {"error": str(e)}
                    ).encode()
        if path == "/trace" or path.startswith("/trace?"):
            return self._route_trace(path)
        if path == "/slo" or path.startswith("/slo?"):
            return 200, "application/json", json.dumps(
                self.slo.report()
            ).encode()
        if path == "/autopilot" or path.startswith("/autopilot?"):
            ap = self.autopilot
            if ap is None:
                from fabric_tpu.control import global_autopilot

                ap = global_autopilot()
            if ap is None:
                return 200, "application/json", json.dumps(
                    {"enabled": False, "configured": False}
                ).encode()
            return 200, "application/json", json.dumps(
                {"configured": True, **ap.report()}
            ).encode()
        if path == "/vitals" or path.startswith("/vitals?"):
            return self._route_vitals(path)
        if path == "/launches" or path.startswith("/launches?"):
            return self._route_launches(path)
        if path == "/txflow" or path.startswith("/txflow?"):
            return self._route_txflow(path)
        if path.startswith("/debug/"):
            return self._route_debug(path)
        return 404, "application/json", b'{"error": "not found"}'

    #: histograms the /trace summary reads (through the locked
    #: snapshot accessors) next to the span trees
    TRACE_SUMMARY_METRICS = (
        "commit_pipeline_stage_seconds",
        "commit_pipeline_overlap_ratio",
        "validator_stage_seconds",
        "host_stage_pool_seconds",
        "sidecar_request_seconds",
        "sidecar_queue_age_seconds",
    )

    def _route_trace(self, path: str):
        """Flight-recorder surface (fabric_tpu.observe): ``/trace``
        serves recent slow blocks (plus the most recent trees and an
        aggregate-stage summary); ``/trace?block=N`` serves one block's
        full span tree.  ``ns=`` selects a non-default ring — a
        colocated sidecar's request trees live under ``ns=sidecar``
        (``/trace?ns=sidecar&block=7`` is request 7), so they never
        shadow peer block numbers."""
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(path).query)
        ns = q.get("ns", [""])[0]
        if "block" in q:
            try:
                num = int(q["block"][0])
            except ValueError:
                return 400, "application/json", b'{"error": "bad block"}'
            tree = self.tracer.block(num, ns=ns)
            if tree is None:
                return 404, "application/json", json.dumps(
                    {"error": f"block {num} not in the flight recorder"
                              + (f" (ns={ns})" if ns else "")}
                ).encode()
            return 200, "application/json", json.dumps(tree).encode()

        summary = {}
        for name in self.TRACE_SUMMARY_METRICS:
            m = self.registry.metric(name)
            if m is None or not hasattr(m, "snapshot"):
                continue
            summary[name] = {
                ",".join(f"{k}={v}" for k, v in key) or "_": {
                    "count": s["count"],
                    "sum_s": round(s["sum"], 6),
                }
                for key, s in sorted(m.snapshot().items())
            }
        ring = self.tracer.blocks(ns=ns)
        # pipeline overlap coverage over the whole ring: what fraction
        # of each block's device_wait the k±window neighbors' host
        # stages actually hid (observe/overlap.py; the deep-pipelining
        # acceptance number).  ?overlap_window=N matches depth N+1.
        from fabric_tpu.observe import overlap as _overlap

        try:
            window = int(q.get("overlap_window", ["2"])[0])
        except ValueError:
            window = 2
        cov = _overlap.coverage_from_roots(
            self.tracer.recent_roots(ns=ns), window=window
        )
        cov.pop("per_block", None)  # the index stays an index
        payload = {
            "enabled": self.tracer.enabled,
            "ring_blocks": self.tracer.ring_blocks,
            "slow_factor": self.tracer.slow_factor,
            "slow_blocks": self.tracer.slow_blocks(),
            "recent_blocks": ring[-4:],
            "blocks_in_ring": [b.get("block") for b in ring],
            "namespaces": self.tracer.namespaces(),
            "pipeline_overlap_coverage": cov,
            "summary": summary,
        }
        if ns:
            payload["ns"] = ns
        return 200, "application/json", json.dumps(payload).encode()

    def _route_vitals(self, path: str):
        """Flight-data recorder surface (fabric_tpu.observe.timeseries
        + .blackbox): ``/vitals`` serves the sampler's sparkline-style
        summaries next to the black-box incident index;
        ``?metric=NAME`` the full trailing series of one metric (every
        label variant); ``?incident=K`` one incident bundle in full.
        Unarmed (the default) answers honestly: enabled false, no
        series, no thread."""
        from urllib.parse import parse_qs, urlparse

        sampler = self.vitals
        if sampler is None:
            from fabric_tpu.observe import timeseries

            sampler = timeseries.global_sampler()
        bb = self.blackbox
        if bb is None:
            from fabric_tpu.observe import blackbox as _blackbox

            bb = _blackbox.global_blackbox()
        q = parse_qs(urlparse(path).query)
        if "incident" in q:
            try:
                seq = int(q["incident"][0])
            except ValueError:
                return 400, "application/json", b'{"error": "bad incident"}'
            bundle = bb.bundle(seq) if bb is not None else None
            if bundle is None:
                return 404, "application/json", json.dumps(
                    {"error": f"incident {seq} not in the black box"}
                ).encode()
            return 200, "application/json", json.dumps(bundle).encode()
        if "metric" in q:
            name = q["metric"][0]
            series = (
                sampler.series(metric=name) if sampler is not None else {}
            )
            if not series:
                return 404, "application/json", json.dumps(
                    {"error": f"no recorded series for metric {name!r}"}
                ).encode()
            variants = series[name]
            label = q.get("label", [None])[0]
            if label is not None:
                # one metric with many label variants used to return
                # every ring; ?label=k=v keeps only the variants that
                # carry that exact pair (or the full label string)
                variants = {
                    ls: v for ls, v in variants.items()
                    if ls == label or label in ls.split(",")
                }
                if not variants:
                    return 404, "application/json", json.dumps(
                        {"error": f"no series for metric {name!r} with "
                                  f"label {label!r}"}
                    ).encode()
            payload = {"metric": name, "series": variants}
            # trace exemplars (ops_metrics histograms): a p99 spike in
            # the trail links to the exact block's trace tree
            from fabric_tpu.ops_metrics import exemplars_report

            ex = exemplars_report(self.registry, metric=name).get(name)
            if ex:
                if label is not None:
                    ex = {
                        ls: v for ls, v in ex.items()
                        if ls == label or label in ls.split(",")
                    }
                if ex:
                    payload["exemplars"] = ex
            return 200, "application/json", json.dumps(payload).encode()
        payload: dict = {"enabled": sampler is not None}
        if sampler is not None:
            payload.update(sampler.report())
        payload["incidents"] = bb.bundles() if bb is not None else []
        return 200, "application/json", json.dumps(payload).encode()

    def _route_launches(self, path: str):
        """Device-time attribution surface (fabric_tpu.observe.ledger):
        per-kernel compile/queue/execute percentiles, program-cache
        hit rates, HBM owner watermarks + a live ``jax.live_arrays()``
        sample, and the last-N raw launch rows.  ``?n=K`` bounds the
        rows, ``?kernel=NAME`` filters them.  Unarmed answers
        honestly: enabled false, no rows."""
        from urllib.parse import parse_qs, urlparse

        led = self.launches
        if led is None:
            from fabric_tpu.observe import ledger as _ledger

            led = _ledger.global_ledger()
        if led is None:
            return 200, "application/json", json.dumps(
                {"enabled": False}
            ).encode()
        q = parse_qs(urlparse(path).query)
        try:
            # <= 0 means no raw rows (rows() pins this — a raw slice
            # would invert the bound via rows[-0:])
            n = int(q.get("n", ["16"])[0])
        except ValueError:
            return 400, "application/json", b'{"error": "bad n"}'
        kernel = q.get("kernel", [None])[0]
        payload = {"enabled": True,
                   **led.report(rows=n, kernel=kernel)}
        from fabric_tpu.observe.ledger import live_device_bytes

        live = live_device_bytes()
        if live is not None:
            payload["live_device_bytes"] = live
        return 200, "application/json", json.dumps(payload).encode()

    def _route_txflow(self, path: str):
        """Per-transaction flow attribution surface
        (fabric_tpu.observe.txflow): stage p50/p99/max, e2e by
        validation outcome, visibility lag (apply-visible minus
        durable-append) and the last-N completed flows.  ``?n=K``
        bounds the rows, ``?tx=TXID`` returns ONE flow's full
        milestone record (completed or still in flight).  Unarmed
        answers honestly: enabled false, no rows."""
        from urllib.parse import parse_qs, urlparse

        j = self.txflow
        if j is None:
            from fabric_tpu.observe import txflow as _txflow

            j = _txflow.global_journal()
        if j is None:
            return 200, "application/json", json.dumps(
                {"enabled": False}
            ).encode()
        q = parse_qs(urlparse(path).query)
        tx = q.get("tx", [None])[0]
        if tx is not None:
            flow = j.lookup(tx)
            if flow is None:
                return 404, "application/json", json.dumps(
                    {"enabled": True, "error": f"no flow for {tx}"}
                ).encode()
            return 200, "application/json", json.dumps(
                {"enabled": True, "flow": flow}
            ).encode()
        try:
            # <= 0 means no raw rows (rows() pins this — a raw slice
            # would invert the bound via rows[-0:])
            n = int(q.get("n", ["16"])[0])
        except ValueError:
            return 400, "application/json", b'{"error": "bad n"}'
        payload = {"enabled": True, **j.report(rows=n)}
        return 200, "application/json", json.dumps(payload).encode()

    def _route_debug(self, path: str):
        """Live profiling surface (the reference's peer.profile pprof
        server, internal/peer/node/start.go:861-876, translated to the
        Python runtime): /debug/stacks dumps every thread's stack;
        /debug/profile?seconds=N runs a wall-clock statistical sampler
        over every live thread and returns a samples/self table."""
        import sys
        import traceback
        from urllib.parse import parse_qs, urlparse

        parsed = urlparse(path)
        if parsed.path == "/debug/stacks":
            import threading

            names = {t.ident: t.name for t in threading.enumerate()}
            out = []
            for tid, frame in sys._current_frames().items():
                out.append(f"--- thread {names.get(tid, tid)} ({tid}) ---")
                out.extend(
                    line.rstrip()
                    for line in traceback.format_stack(frame)
                )
            return 200, "text/plain", "\n".join(out).encode()
        if parsed.path == "/debug/profile":
            # NOTE: blocks THIS request for the sampling window; other
            # connections keep being served (per-connection tasks).
            # A STATISTICAL sampler over sys._current_frames(), not
            # cProfile: the commit/validate hot path runs in
            # ThreadPoolExecutor workers, and a tracing profiler
            # enabled on the event-loop thread would systematically
            # miss it — the wall-clock sampler sees every thread.
            import threading

            try:
                seconds = float(
                    parse_qs(parsed.query).get("seconds", ["5"])[0]
                )
            except ValueError:
                return 400, "application/json", b'{"error": "bad seconds"}'
            seconds = max(0.1, min(seconds, 60.0))

            async def run():
                interval = 0.005
                counts: dict[tuple, int] = {}
                nsamples = 0
                names = {}
                deadline = asyncio.get_event_loop().time() + seconds
                while asyncio.get_event_loop().time() < deadline:
                    names = {
                        t.ident: t.name for t in threading.enumerate()
                    }
                    for tid, frame in sys._current_frames().items():
                        nsamples += 1
                        # dedupe per stack: a recursive function counts
                        # ONCE per sample, not once per stack level
                        stack_keys = set()
                        f = frame
                        while f is not None:
                            co = f.f_code
                            stack_keys.add(
                                (names.get(tid, str(tid)),
                                 co.co_filename, co.co_name, f is frame)
                            )
                            f = f.f_back
                        for key in stack_keys:
                            counts[key] = counts.get(key, 0) + 1
                    await asyncio.sleep(interval)
                lines = [
                    f"wall-clock samples over {seconds}s "
                    f"({nsamples} thread-samples, {interval * 1000:.0f}ms "
                    "interval); 'self' = frame was on top",
                    f"{'samples':>8} {'self':>6}  location",
                ]
                agg: dict[tuple, list] = {}
                for (tname, fn, func, is_top), cnt in counts.items():
                    row = agg.setdefault((tname, fn, func), [0, 0])
                    row[0] += cnt
                    if is_top:
                        row[1] += cnt
                for (tname, fn, func), (tot, self_cnt) in sorted(
                    agg.items(), key=lambda kv: -kv[1][0]
                )[:80]:
                    short = fn.rsplit("/", 1)[-1]
                    lines.append(
                        f"{tot:>8} {self_cnt:>6}  "
                        f"[{tname}] {short}:{func}"
                    )
                return "\n".join(lines) + "\n"

            return run  # the connection handler awaits coroutine routes
        return 404, "application/json", b'{"error": "not found"}'


def apply_logspec(spec: str) -> None:
    """FABRIC_LOGGING_SPEC-style: 'info' or
    'warning:fabric_tpu.peer=debug:fabric_tpu.ordering=error'."""
    parts = [p for p in spec.split(":") if p]
    for p in parts:
        if "=" in p:
            name, _, level = p.partition("=")
            logging.getLogger(name).setLevel(level.upper())
        else:
            logging.getLogger("fabric_tpu").setLevel(p.upper())
